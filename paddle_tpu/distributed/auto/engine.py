"""The composed model-parallel train step: GSPMD tensor parallelism +
1F1B pipeline stages + ZeRO-sharded optimizer states, one compiled SPMD
program over a dp×pp×tp mesh.

This is the subsystem PAPER.md's layer map calls "fleet = GSPMD
shardings over jax.sharding.Mesh (ICI)": sharding RULES (rules.py) say
where every parameter lives, the Megatron block math is shared with
models/gpt_hybrid.py (column/row splits, vocab-parallel embedding and
cross entropy), the 1F1B microbatch scheduler (pipeline.py) drives the
'pp' axis, and the AdamW update runs SHARD-LOCAL over dp with
reduce-scattered grads (zero.py) — so a model whose replicated
params+moments cannot fit one device trains on the host mesh.

The whole step — forward, backward, per-axis grad reduction, global-norm
clip, sharded AdamW, param regather — is ONE buffer-donated jitted
shard_map program; XLA overlaps the collectives with compute.  The
builder derives a static per-step collective plan (one dp reduce-scatter
per leaf "bucket", the tp psums the block math issues per tick, the pp
ppermute handoffs per schedule) and the step wrapper publishes it into
the ``sharding.*`` registry family — the contract bench.py
--model-parallel asserts.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework import compile_cache as _cc
from ...framework import jax_compat
from ...framework.jax_compat import shard_map, partition_spec as P
from ...optimizer.functional import adamw_update
from . import pipeline as pipe_mod
from . import rules as rules_mod
from . import zero as zero_mod
from .stats import _sharding_stats

MESH_AXES = ("dp", "pp", "tp", "sp")

# module-level: the donated MP step cache outlives any one
# make_train_step call (repeated builders with identical identity
# reuse one compiled program)
_mp_step_site = _cc.site("mp.train_step", maxsize=8)


def make_mesh(dp=1, tp=1, pp=1, devices=None):
    """The subsystem's mesh: axes ('dp', 'pp', 'tp', 'sp') with sp
    pinned to 1 (sequence parallelism rides models/gpt_hybrid.py's ring
    attention; the auto engine schedules dp/tp/pp).  Routed through
    framework/jax_compat.py per the standing constraint."""
    devices = list(devices if devices is not None else jax.devices())
    n = dp * tp * pp
    if len(devices) < n:
        raise ValueError(
            f"mesh dp={dp} tp={tp} pp={pp} needs {n} devices, "
            f"have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(dp, pp, tp, 1)
    return jax_compat.make_mesh(arr, MESH_AXES)


def mesh_axis_sizes(mesh):
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def _gpt_shapes(cfg):
    from ...models import gpt
    return jax.eval_shape(lambda k: gpt.init_params(cfg, k),
                          jax.random.PRNGKey(0))


def _resolve_specs(cfg, mesh, family):
    specs = rules_mod.prune_to_mesh(rules_mod.rules_for(family, cfg), mesh)
    bad = rules_mod.validate(specs, _gpt_shapes(cfg), mesh)
    if bad:
        raise ValueError(f"sharding rules don't divide {family} shapes "
                         f"on this mesh: {bad}")
    return specs


# --------------------------------------------------------------------------
# static collective plan
# --------------------------------------------------------------------------

class CollectivePlan:
    """What the compiled FORWARD program issues per step, derived from
    the schedule and rules (reverse-mode AD roughly doubles the tp/pp
    counts at runtime; dp grad reductions appear exactly once).  This is
    bookkeeping the host publishes — nothing here is traced."""

    def __init__(self, cfg, mesh, sched, batch, seq):
        sizes = mesh_axis_sizes(mesh)
        dp, tp, pp = sizes["dp"], sizes["tp"], sizes["pp"]
        shapes = _gpt_shapes(cfg)
        leaves = jax.tree_util.tree_leaves(shapes)
        nbytes = [int(np.prod(l.shape)) * 4 for l in leaves]  # fp32 grads

        # dp: ONE reduce-scatter (stage>=2) / psum per param leaf — the
        # leaf IS the bucket on this substrate (grads are consumed by the
        # in-step sharded update, never re-bucketed host-side)
        self.dp_collectives = len(leaves) if dp > 1 else 0
        self.dp_bytes = sum(nbytes) if dp > 1 else 0

        # tp: 2 psums per block application + embed + 3 xent psums; with
        # a pipeline the stage body executes its layer range every tick
        # (bubble ticks included — SPMD programs don't skip)
        if tp > 1:
            layer_apps = (sched.n_ticks * (cfg.num_layers // pp)
                          if pp > 1 else cfg.num_layers)
            self.tp_collectives = 2 * layer_apps + 1 + 3
            act = (batch // max(dp, 1)) * seq * cfg.hidden_size * 4
            self.tp_bytes = 2 * layer_apps * act
        else:
            self.tp_collectives = 0
            self.tp_bytes = 0

        # pp: one ppermute handoff per tick + the output fan-out psum
        if pp > 1:
            self.pp_collectives = sched.handoffs() + 1
            mb_act = ((batch // max(dp, 1)) // sched.n_microbatch) \
                * seq * cfg.hidden_size * 4
            self.pp_bytes = sched.handoffs() * mb_act
        else:
            self.pp_collectives = 0
            self.pp_bytes = 0

        self.bubble_fraction = sched.bubble_fraction if pp > 1 else 0.0
        self.n_leaves = len(leaves)

    def publish(self):
        """Add one step's worth of the plan to the sharding.* family."""
        _sharding_stats.inc("steps")
        _sharding_stats.inc("collectives_dp", self.dp_collectives)
        _sharding_stats.inc("collectives_tp", self.tp_collectives)
        _sharding_stats.inc("collectives_pp", self.pp_collectives)
        _sharding_stats.inc("bytes_dp", self.dp_bytes)
        _sharding_stats.inc("bytes_tp", self.tp_bytes)
        _sharding_stats.inc("bytes_pp", self.pp_bytes)


# --------------------------------------------------------------------------
# state init
# --------------------------------------------------------------------------

def init_state(cfg, mesh, key, zero_stage=2, family="gpt",
               moment_dtype=jnp.float32):
    """(params, m, v) placed by the rules: params tp/pp-sharded per the
    registry, Adam moments additionally dp-sharded on their zero axis
    (``zero_stage>=1``).  Publishes the per-device byte gauges the bench
    asserts (``sharding.param_bytes_per_device`` /
    ``opt_state_bytes_per_device`` / ``opt_state_bytes_replicated``)."""
    from ...models import gpt
    specs = _resolve_specs(cfg, mesh, family)
    params = rules_mod.place(gpt.init_params(cfg, key), mesh, specs)
    if zero_stage >= 1:
        mspecs, _ = zero_mod.zero_specs(specs, params, mesh, record=False)
    else:
        mspecs = specs
    def fresh_zeros():
        # a NEW zeros tree per moment: placing one tree twice can
        # no-op device_put into ALIASED buffers (same array, same
        # sharding), and the donated step then donates one buffer twice
        return jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, moment_dtype), params)
    m = rules_mod.place(fresh_zeros(), mesh, mspecs)
    v = rules_mod.place(fresh_zeros(), mesh, mspecs)

    mdt = jnp.dtype(moment_dtype).itemsize
    replicated = sum(int(np.prod(l.shape)) * mdt * 2
                     for l in jax.tree_util.tree_leaves(params))
    _sharding_stats["param_bytes_per_device"] = \
        rules_mod.bytes_per_device(params)
    _sharding_stats["opt_state_bytes_per_device"] = (
        rules_mod.bytes_per_device(m) + rules_mod.bytes_per_device(v))
    _sharding_stats["opt_state_bytes_replicated"] = replicated
    return params, m, v


# --------------------------------------------------------------------------
# the composed train step
# --------------------------------------------------------------------------

def make_train_step(cfg, mesh, n_microbatch=1, zero_stage=2,
                    beta1=0.9, beta2=0.95, eps=1e-8, weight_decay=0.1,
                    clip_norm=1.0, xent_chunks=1, family="gpt"):
    """Jitted ``step(params, m, v, t, tokens, labels, lr) ->
    (params, m, v, loss)`` over the auto mesh.

    tokens/labels: GLOBAL [B, N] int32, batch sharded over dp; t: int32
    1-based step count; params/m/v from :func:`init_state` with the same
    ``zero_stage``.  ``zero_stage``: 0 replicated moments (the bench
    baseline), 1 moments dp-sharded with full grad psums, 2 moments
    dp-sharded with grad reduce-scatter (a fully cross-dp-reduced grad
    never materializes).  The returned callable carries ``.plan``
    (:class:`CollectivePlan`) and ``.schedule`` and publishes the plan
    into ``sharding.*`` per call."""
    from ...models import gpt_hybrid as H
    if family != "gpt":
        raise NotImplementedError(
            "the composed train step is gpt-family for now; bert/moe "
            "register layouts (rules.py) for the placement APIs")
    sp_size, pp_size = H._check_mesh(cfg, mesh)
    sizes = mesh_axis_sizes(mesh)
    specs = _resolve_specs(cfg, mesh, family)
    shapes = _gpt_shapes(cfg)
    if zero_stage >= 1:
        mspecs, zaxes = zero_mod.zero_specs(specs, shapes, mesh)
    else:
        mspecs = specs
        zaxes = jax.tree_util.tree_map(lambda _: -1, specs,
                                       is_leaf=rules_mod._is_spec)
    sched = pipe_mod.Schedule(n_microbatch, pp_size)
    pipe_fn = pipe_mod.pipeline_forward
    mesh_size = mesh.size

    def step(params, m, v, t, tokens, labels, lr):
        loss, grads = jax.value_and_grad(
            lambda p: H._fwd_loss(cfg, sp_size, pp_size, n_microbatch,
                                  p, tokens, labels,
                                  xent_chunks=xent_chunks,
                                  pipeline_fn=pipe_fn))(params)

        def red(spec, zax, g):
            # psum over the leaf's replicated axes EXCEPT dp, then the
            # dp reduction is the ZeRO scatter (or psum for -1 leaves);
            # total = sum over every copy, /mesh_size = the mean grad
            sharded = set(rules_mod.spec_axes(spec))
            axes = tuple(a for a in MESH_AXES
                         if a not in sharded and a != "dp")
            if axes:
                g = jax.lax.psum(g, axes)
            g = zero_mod.scatter_grad(g.astype(jnp.float32), zax,
                                      zero_stage)
            return g / mesh_size

        gshards = jax.tree_util.tree_map(red, specs, zaxes, grads,
                                         is_leaf=rules_mod._is_spec)

        if clip_norm:
            def sumsq(spec, zax, g):
                sq = jnp.sum(jnp.square(g))
                axes = tuple(rules_mod.spec_axes(spec))
                if zax >= 0:
                    axes = axes + ("dp",)
                return jax.lax.psum(sq, axes) if axes else sq
            sqs = jax.tree_util.tree_map(sumsq, specs, zaxes, gshards,
                                         is_leaf=rules_mod._is_spec)
            gn = jnp.sqrt(sum(jax.tree_util.tree_leaves(sqs)))
            scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gn, 1e-12))
            gshards = jax.tree_util.tree_map(lambda g: g * scale, gshards)

        tf = t.astype(jnp.float32)

        def upd(path, zax, p, g, mm, vv):
            leaf = str(getattr(path[-1], "key", path[-1]))
            decay = leaf not in H.NO_DECAY and leaf not in H.LN_NAMES
            psh = zero_mod.param_shard(p, zax)
            np_, nm_, nv_ = adamw_update(psh, g, mm, vv, lr, tf, beta1,
                                         beta2, eps, weight_decay, decay)
            return (zero_mod.gather_param_shard(np_, zax), nm_, nv_)

        out = jax.tree_util.tree_map_with_path(upd, zaxes, params,
                                               gshards, m, v)
        tup = lambda o: isinstance(o, tuple) and len(o) == 3  # noqa: E731
        new_p = jax.tree_util.tree_map(lambda o: o[0], out, is_leaf=tup)
        new_m = jax.tree_util.tree_map(lambda o: o[1], out, is_leaf=tup)
        new_v = jax.tree_util.tree_map(lambda o: o[2], out, is_leaf=tup)
        return new_p, new_m, new_v, loss

    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(specs, mspecs, mspecs, P(), P("dp", "sp"),
                  P("dp", "sp"), P()),
        out_specs=(specs, mspecs, mspecs, P()),
        check_vma=False)
    # the donated MP step rides the unified compile layer: two
    # make_train_step calls with an identical (cfg, mesh, schedule,
    # hyper) identity share ONE jitted program instead of re-tracing —
    # the step is deterministic in exactly these inputs (params/moments
    # are operands).  No AOT stable_key: shard_map programs are bound to
    # the live mesh's device topology, which the artifact store cannot
    # attest across processes.
    import dataclasses as _dc
    _mp_key = _cc.make_key(
        "mp_step",
        tuple(sorted((k, str(v))
                     for k, v in _dc.asdict(cfg).items())),
        tuple(mesh.axis_names), tuple(mesh.devices.shape),
        tuple(int(d.id) for d in mesh.devices.flat),
        n_microbatch, zero_stage, beta1, beta2, eps, weight_decay,
        clip_norm, xent_chunks, family,
        donate=(0, 1, 2))
    jitted = _mp_step_site.get(
        _mp_key, lambda: jax.jit(sharded, donate_argnums=(0, 1, 2)))

    # the host wrapper publishes the static plan per launch; batch/seq
    # for byte accounting are read from the first call's operands
    plan_box = [None]

    def step_fn(params, m, v, t, tokens, labels, lr):
        if plan_box[0] is None:
            plan_box[0] = CollectivePlan(cfg, mesh, sched,
                                         tokens.shape[0], tokens.shape[1])
            step_fn.plan = plan_box[0]
            _sharding_stats["bubble_fraction_pct"] = round(
                100.0 * plan_box[0].bubble_fraction, 2)
        out = jitted(params, m, v, jnp.int32(t), tokens, labels,
                     jnp.float32(lr))
        plan_box[0].publish()
        return out

    step_fn.plan = None
    step_fn.schedule = sched
    step_fn.zero_stage = zero_stage
    step_fn.mesh = mesh
    return step_fn


def make_forward(cfg, mesh, family="gpt"):
    """Sharded inference forward (params, tokens) -> full logits — the
    TP logit-parity surface.  Delegates to models/gpt_hybrid.py (same
    block math as the train step)."""
    from ...models import gpt_hybrid as H
    if family != "gpt":
        raise NotImplementedError("forward parity surface is gpt-family")
    return H.make_forward(cfg, mesh)
