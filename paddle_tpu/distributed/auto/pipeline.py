"""Pipeline parallelism over the 'pp' mesh axis: layer-range stage
assignment + a 1F1B-style microbatch schedule.

Stages run inside ``shard_map`` (via framework/jax_compat.py): each pp
rank holds one contiguous LAYER RANGE of the stacked block parameters
(the leading [L] axis split over 'pp'), activations hop stage-to-stage
through ``jax.lax.ppermute`` (XLA collective-permute on ICI), and the
microbatch schedule keeps every stage busy outside the fill/drain bubble.

1F1B here is the schedule's SHAPE, not hand-written backward code: the
forward loop runs the 1F1B tick table (stage s touches microbatch t-s at
tick t; one in-flight activation per stage), and reverse-mode AD through
the loop replays ticks last-to-first — in the transposed program each
microbatch's backward runs as soon as its forward frame is reached, the
one-forward-one-backward interleave that bounds live activations to
O(stages) (with ``remat`` on the blocks) instead of O(microbatches).
:class:`Schedule` exposes the tick table and the bubble fraction so the
observability layer reports what the compiled loop actually does.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.jax_compat import shard_map, axis_size as _axis_size
from ...framework.jax_compat import partition_spec as P


class StageAssignment:
    """Contiguous layer ranges per pipeline stage.

    Default: equal split of ``num_layers`` over ``n_stages``.  Explicit
    ``ranges`` ([(start, end), ...], end-exclusive) must cover the stack
    contiguously and — because shard_map splits the stacked [L] parameter
    axis evenly — be equal-sized; uneven load-balancing belongs in layer
    COST, not count, on this substrate."""

    def __init__(self, num_layers, n_stages, ranges=None):
        if ranges is None:
            if num_layers % n_stages:
                raise ValueError(
                    f"num_layers {num_layers} must divide by pp stages "
                    f"{n_stages} (or pass explicit equal ranges)")
            per = num_layers // n_stages
            ranges = [(s * per, (s + 1) * per) for s in range(n_stages)]
        ranges = [tuple(r) for r in ranges]
        if len(ranges) != n_stages:
            raise ValueError(f"{len(ranges)} ranges for {n_stages} stages")
        sizes = {e - s for s, e in ranges}
        if len(sizes) != 1:
            raise ValueError(
                f"stage ranges must be equal-sized (shard_map splits the "
                f"stacked layer axis evenly), got {ranges}")
        prev = 0
        for s, e in ranges:
            if s != prev or e <= s:
                raise ValueError(f"ranges must tile [0,{num_layers}) "
                                 f"contiguously, got {ranges}")
            prev = e
        if prev != num_layers:
            raise ValueError(f"ranges cover [0,{prev}), model has "
                             f"{num_layers} layers")
        self.num_layers = num_layers
        self.n_stages = n_stages
        self.ranges = tuple(ranges)
        self.layers_per_stage = self.ranges[0][1] - self.ranges[0][0]

    def stage_of_layer(self, layer):
        return layer // self.layers_per_stage


class Schedule:
    """1F1B tick table for ``n_microbatch`` over ``n_stages``.

    ``ticks`` is the forward table: entry [t][s] is the microbatch stage
    ``s`` forwards at tick ``t`` (None in the bubble).  The backward is
    its time-reverse under AD.  ``bubble_fraction`` is the classic
    (p-1)/(m+p-1) idle share per stage."""

    def __init__(self, n_microbatch, n_stages):
        if n_microbatch < 1:
            raise ValueError("n_microbatch must be >= 1")
        self.n_microbatch = n_microbatch
        self.n_stages = n_stages
        self.n_ticks = n_microbatch + n_stages - 1
        self.ticks = tuple(
            tuple((t - s) if 0 <= (t - s) < n_microbatch else None
                  for s in range(n_stages))
            for t in range(self.n_ticks))

    @property
    def bubble_fraction(self):
        return (self.n_stages - 1) / self.n_ticks

    def handoffs(self):
        """Number of ppermute hops the compiled loop performs per
        forward pass (one per tick; the backward doubles it under AD)."""
        return self.n_ticks


def pipeline_forward(stage_fn, x_global, n_microbatch, axis_name="pp"):
    """Run the 1F1B forward schedule inside an enclosing shard_map.

    ``stage_fn(x) -> y`` applies THIS stage's layer range (closing over
    the stage's parameter shard — shard_map already split the stacked
    leading axis).  ``x_global``: [B, ...] pp-replicated input.  Returns
    the final-stage output broadcast to every stage ([B, ...]), so the
    loss (and its backward) is identical on all pp ranks.

    Tick t: stage 0 ingests microbatch t (while any remain), every stage
    applies its layers to the activation it holds, the finished
    microbatch (t - (p-1) at the last stage) is written out, and
    activations rotate one hop along the 'pp' ring via ppermute."""
    idx = jax.lax.axis_index(axis_name)
    size = _axis_size(axis_name)
    B = x_global.shape[0]
    if B % n_microbatch:
        raise ValueError(
            f"batch {B} must divide by n_microbatch {n_microbatch}")
    mb = B // n_microbatch
    micro = x_global.reshape(n_microbatch, mb, *x_global.shape[1:])
    sched = Schedule(n_microbatch, size)

    state = jnp.zeros_like(micro[0])          # the one in-flight activation
    outputs = jnp.zeros_like(micro)

    def tick(t, carry):
        state, outputs = carry
        # stage 0's schedule entry: forward microbatch t while they last
        feed = micro[jnp.minimum(t, n_microbatch - 1)]
        state = jnp.where(idx == 0,
                          jnp.where(t < n_microbatch, feed, state), state)
        out = stage_fn(state)
        # last stage retires microbatch t - (p-1) once the fill completes
        done_idx = t - (size - 1)
        write = (idx == size - 1) & (done_idx >= 0)
        outputs = jax.lax.cond(
            write,
            lambda o: o.at[jnp.maximum(done_idx, 0)].set(out),
            lambda o: o, outputs)
        # collective-permute handoff: activation moves one stage down
        perm = [(j, (j + 1) % size) for j in range(size)]
        state = jax.lax.ppermute(out, axis_name, perm)
        return state, outputs

    state, outputs = jax.lax.fori_loop(0, sched.n_ticks, tick,
                                       (state, outputs))
    # ppermute is one-to-one; fan the finished microbatches (resident on
    # the last stage) out to every stage with a masked psum
    if size > 1:
        outputs = jax.lax.psum(
            jnp.where(idx == size - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
    return outputs.reshape(B, *outputs.shape[2:])


def pipeline_stage_loop(stage_fn, micro, carry, axis_name="pp"):
    """The 1F1B tick loop with stage-local CARRY — the serving variant
    (ISSUE 20).  :func:`pipeline_forward` assumes a stateless stage;
    the serving decode/prefill stages thread their paged KV pools
    through every tick (each microbatch APPENDS to the stage's pool),
    and bubble ticks must be able to mask that side effect.

    ``micro``: [M, mb, ...] pp-replicated stacked stage-0 feeds (e.g.
    embedded microbatch activations).  ``stage_fn(x, carry, m, valid)
    -> (y, carry)`` applies this stage's layer range to the in-flight
    activation ``x`` [mb, ...]: ``m`` is the (traced, already clipped
    into [0, M)) microbatch index this tick nominally processes and
    ``valid`` a traced bool that is False in fill/drain bubble ticks —
    the stage gathers its per-microbatch side operands at ``m`` and
    aims writes at scratch when not ``valid``.  Activation shape is
    preserved (y.shape == x.shape, the transformer block contract).

    Returns ``(outputs [M, mb, ...], carry)``: the LAST stage's
    per-microbatch outputs fanned out to every stage via masked psum
    (same fan-out as pipeline_forward), plus the threaded carry."""
    idx = jax.lax.axis_index(axis_name)
    size = _axis_size(axis_name)
    M = micro.shape[0]
    sched = Schedule(M, size)

    state = jnp.zeros_like(micro[0])
    outputs = jnp.zeros_like(micro)

    def tick(t, tc):
        state, carry, outputs = tc
        m = t - idx
        valid = (m >= 0) & (m < M)
        msafe = jnp.clip(m, 0, M - 1)
        feed = micro[jnp.minimum(t, M - 1)]
        x = jnp.where(idx == 0,
                      jnp.where(t < M, feed, state), state)
        y, carry = stage_fn(x, carry, msafe, valid)
        write = (idx == size - 1) & valid
        outputs = jax.lax.cond(
            write, lambda o: o.at[msafe].set(y), lambda o: o, outputs)
        perm = [(j, (j + 1) % size) for j in range(size)]
        state = jax.lax.ppermute(y, axis_name, perm)
        return state, carry, outputs

    state, carry, outputs = jax.lax.fori_loop(
        0, sched.n_ticks, tick, (state, carry, outputs))
    if size > 1:
        outputs = jax.lax.psum(
            jnp.where(idx == size - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
    return outputs, carry


def make_pipelined(mesh, stage_fn, n_microbatch, axis_name="pp"):
    """Standalone pipelined forward over GLOBAL stacked params (for tests
    and single-purpose inference): ``stage_fn(stage_params, x) -> y``
    with stage_params' leading layer axis already split over
    ``axis_name``.  The composed train step builds its own shard_map
    (engine.py) — this wrapper exists so the scheduler is exercisable
    without the full engine."""
    def run(params_stacked, x):
        def body(p_local, xg):
            return pipeline_forward(lambda xx: stage_fn(p_local, xx),
                                    xg, n_microbatch, axis_name)
        return shard_map(
            body, mesh=mesh,
            in_specs=(P(axis_name), P()),
            out_specs=P(),
            check_vma=False,
        )(params_stacked, x)
    return run
