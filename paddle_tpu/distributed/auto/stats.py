"""``sharding.*`` registry family — the model-parallel subsystem's
observability surface (per-axis collective counts/bytes, pipeline bubble
fraction, per-device state bytes).

A VIEW over the observability registry (same storage as
``metrics.snapshot()`` / ``profiler.fast_path_summary()["sharding"]``).
Counts are bumped HOST-SIDE from the engine's static per-step collective
plan — inside the compiled program there is nothing to count, so the
builder derives how many collectives of which size each step issues per
axis and the step wrapper adds them per call.  That makes the counters a
CONTRACT ("1 reduce-scatter per bucket per step on dp"), which is
exactly what bench.py --model-parallel asserts.
"""
from __future__ import annotations

from ...observability import metrics as _metrics

_sharding_stats = _metrics.stats_family("sharding", {
    "steps": 0,                    # composed train-step launches
    "collectives_dp": 0,           # grad reduce-scatters/psums on dp
    "collectives_tp": 0,           # block/embed/xent psums on tp
    "collectives_pp": 0,           # ppermute handoffs + output fan-out
    "bytes_dp": 0,                 # payload bytes entering dp collectives
    "bytes_tp": 0,
    "bytes_pp": 0,
    "zero_sharded_leaves": 0,      # moment leaves dp-sharded
    "zero_replicated_leaves": 0,   # leaves with no dp-divisible axis
    "bubble_fraction_pct": 0,      # 100 * (pp-1)/(micro+pp-1), last built
    "param_bytes_per_device": 0,   # gauges: last engine init
    "opt_state_bytes_per_device": 0,
    "opt_state_bytes_replicated": 0,  # what replication WOULD have cost
})


def sharding_stats():
    """Dict snapshot plus the derived ZeRO shrink factor the bench
    asserts (replicated-moment bytes / per-device moment bytes)."""
    s = dict(_sharding_stats)
    per_dev = s["opt_state_bytes_per_device"]
    s["opt_state_shrink"] = (
        round(s["opt_state_bytes_replicated"] / per_dev, 4)
        if per_dev else 0.0)
    return s


def reset_sharding_stats():
    _sharding_stats.reset()
