"""Sharding-rule registry: model family -> PartitionSpec pytrees.

GSPMD (Xu et al.) partitions a single-device program from per-tensor
sharding annotations; the only model-specific knowledge the partitioner
needs is WHICH axis of which parameter to split.  This registry is that
knowledge, centralized: each model family (gpt / bert / moe / ...)
registers a provider ``fn(cfg) -> spec pytree`` matching its
``init_params`` structure, and every consumer — the composed train step
(engine.py), eager placement (zero.py), fleet's legacy
``distributed_model`` — resolves layouts here instead of hand-writing
PartitionSpecs per call site.

The built-in rules are the Megatron-LM layouts (Shoeybi et al.): QKV and
FFN up-projections column-split over 'tp' (attention heads divide across
ranks), attention output and FFN down-projections row-split (partial
sums made whole by ONE psum each — the two allreduces/block recipe),
vocab-parallel embeddings, and the stacked layer axis split over 'pp'.
All sharding types route through framework/jax_compat.py (standing
ROADMAP constraint).
"""
from __future__ import annotations

import jax

from ...framework.jax_compat import named_sharding, partition_spec as P

_REGISTRY = {}       # family -> fn(cfg) -> spec pytree


def register_rules(family, fn=None):
    """Register ``fn(cfg) -> PartitionSpec pytree`` for ``family``.
    Usable as a decorator: ``@register_rules("gpt")``.  Re-registration
    replaces (models re-imported under test harnesses must not error)."""
    def _do(f):
        _REGISTRY[family] = f
        return f
    return _do if fn is None else _do(fn)


def _ensure_builtin(family):
    """Lazy-load the built-in providers: rules live WITH their model
    (``models/gpt.py::sharding_rules`` etc.) so layout and init_params
    can't drift apart; the model module is imported on first resolve and
    its ``sharding_rules`` hook registered — model files never import
    this package, so there is no import cycle."""
    if family in _REGISTRY:
        return
    import importlib
    mod = {"gpt": "paddle_tpu.models.gpt",
           "bert": "paddle_tpu.models.bert",
           "moe": "paddle_tpu.parallel.moe"}.get(family)
    if mod is not None:
        fn = getattr(importlib.import_module(mod), "sharding_rules", None)
        if fn is not None:
            _REGISTRY[family] = fn


def rules_for(family, cfg=None):
    """The registered spec pytree for ``family`` (KeyError with the known
    families named when unregistered)."""
    _ensure_builtin(family)
    fn = _REGISTRY.get(family)
    if fn is None:
        raise KeyError(
            f"no sharding rules registered for {family!r}; known: "
            f"{sorted(_REGISTRY)}")
    return fn(cfg)


def registered_families():
    for fam in ("gpt", "bert", "moe"):
        _ensure_builtin(fam)
    return sorted(_REGISTRY)


# --------------------------------------------------------------------------
# spec utilities (shared by engine.py / zero.py / legacy placement)
# --------------------------------------------------------------------------

def spec_axes(spec):
    """Flat tuple of mesh-axis names a PartitionSpec shards over."""
    return tuple(a for part in spec if part is not None
                 for a in ((part,) if isinstance(part, str) else part))


def replicated_like(specs):
    """Same tree shape, every leaf fully replicated."""
    return jax.tree_util.tree_map(
        lambda _: P(), specs, is_leaf=_is_spec)


def _is_spec(x):
    from ...framework.jax_compat import partition_spec_class
    return isinstance(x, partition_spec_class())


def quantized_like(specs, params):
    """Mirror ``specs`` onto a possibly weight-quantized param tree:
    wherever ``params`` holds a quantized ``{"qw"/"qw_dyn", "scale"}``
    dict leaf (models/gpt.py::quantize_params) at a position the rules
    carry a plain weight spec, expand the spec into a matching dict —
    the int8/fp8 payload keeps the fp weight's column/row split (same
    shape, same axes), while the per-output-channel scale keeps every
    placement EXCEPT the contraction axis (its dim collapsed to 1 in
    the absmax reduction, so a row split there would not divide; and
    because per-output scales distribute over the contraction-axis
    partial sums, replicating them is numerically exact, not an
    approximation).  Non-quantized leaves pass through untouched, so
    the result structurally mirrors ``params`` and feeds straight into
    :func:`validate` / :func:`place`."""
    def expand(spec, leaf):
        if not (isinstance(leaf, dict) and "scale" in leaf
                and ("qw" in leaf or "qw_dyn" in leaf)):
            return spec
        parts = tuple(spec)
        sparts = (parts[:1] + (None,) + parts[2:]
                  if len(parts) > 1 else parts)
        qkey = "qw" if "qw" in leaf else "qw_dyn"
        return {qkey: P(*parts), "scale": P(*sparts)}

    return jax.tree_util.tree_map(expand, specs, params, is_leaf=_is_spec)


def prune_to_mesh(specs, mesh):
    """Drop axis names the mesh doesn't carry (or carries at size 1) from
    every leaf spec, so one rule set serves any dp/tp/pp slice: a tp-only
    mesh reads the same gpt rules as the full 2x2x2 one."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    def keep(name):
        return sizes.get(name, 1) > 1

    def prune_part(part):
        if part is None:
            return None
        if isinstance(part, str):
            return part if keep(part) else None
        kept = tuple(a for a in part if keep(a))
        return kept if kept else None

    def prune(spec):
        parts = tuple(prune_part(p) for p in spec)
        while parts and parts[-1] is None:
            parts = parts[:-1]
        return P(*parts)

    return jax.tree_util.tree_map(prune, specs, is_leaf=_is_spec)


def shardings(mesh, specs):
    """Spec pytree -> NamedSharding pytree (through jax_compat)."""
    return jax.tree_util.tree_map(
        lambda s: named_sharding(mesh, s), specs, is_leaf=_is_spec)


def place(tree, mesh, specs):
    """device_put every leaf of ``tree`` with its rule's NamedSharding.
    Leaves whose spec doesn't divide their shape raise — a silent
    replication here is exactly the round-2 verdict bug class."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, named_sharding(mesh, s)),
        tree, specs)


def validate(specs, shapes_tree, mesh):
    """Check every sharded dim divides by the product of its mesh axes;
    returns a list of (path, spec, shape) violations instead of letting
    device_put raise one leaf at a time."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    bad = []

    def one(path, spec, x):
        shape = tuple(x.shape) if hasattr(x, "shape") else tuple(x)
        for i, part in enumerate(spec):
            if part is None:
                continue
            names = (part,) if isinstance(part, str) else part
            div = 1
            for nm in names:
                div *= sizes.get(nm, 1)
            if i >= len(shape) or shape[i] % div:
                bad.append((jax.tree_util.keystr(path), spec, shape))
                return

    jax.tree_util.tree_map_with_path(one, specs, shapes_tree,
                                     is_leaf=_is_spec)
    return bad


def bytes_per_device(tree):
    """Sum of the addressable shard bytes of every leaf — the per-device
    memory a sharded pytree actually pins (the ZeRO/TP memory proof)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            total += shards[0].data.size * shards[0].data.dtype.itemsize
        else:
            total += leaf.size * jax.numpy.dtype(leaf.dtype).itemsize
    return total
