"""paddle.distributed.utils — launcher plumbing (ref:
python/paddle/distributed/utils.py: the Cluster/Pod/Trainer descriptors
and local process management the reference launch.py builds on).  The
TPU-native launcher (distributed/launch.py) bootstraps jax.distributed
instead of NCCL; these helpers keep the reference surface for scripts
that orchestrate their own pods."""
from __future__ import annotations

import logging
import os
import signal
import socket
import subprocess
import time

__all__ = ["Cluster", "Pod", "Trainer", "TrainerProc", "get_cluster",
           "get_host_name_ip", "find_free_ports", "get_logger",
           "add_arguments", "start_local_trainers",
           "terminate_local_procs", "watch_local_trainers",
           "pull_worker_log", "Hdfs", "JobServer"]


class Trainer:
    def __init__(self):
        self.gpus = []          # accelerator ordinals (TPU chips here)
        self.endpoint = None
        self.rank = None

    def __str__(self):
        return f"Trainer(rank={self.rank}, endpoint={self.endpoint})"

    def __eq__(self, other):
        return (self.rank == other.rank and self.endpoint == other.endpoint
                and self.gpus == other.gpus)

    def __ne__(self, other):
        return not self.__eq__(other)


class Pod:
    def __init__(self):
        self.rank = None
        self.id = None
        self.addr = None
        self.port = None
        self.trainers = []

    def __str__(self):
        return (f"Pod(rank={self.rank}, addr={self.addr}, "
                f"trainers={len(self.trainers)})")

    def rank_of_trainer(self, t):
        return self.trainers.index(t)


class Cluster:
    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods = []
        self.hdfs = hdfs

    def trainers_nranks(self):
        return len(self.trainers_endpoints())

    def trainers_endpoints(self):
        return [t.endpoint for p in self.pods for t in p.trainers]

    def pods_endpoints(self):
        return [f"{p.addr}:{p.port}" for p in self.pods]

    def world_device_ids(self):
        return [t.gpus for p in self.pods for t in p.trainers]

    def __str__(self):
        return f"Cluster(pods={len(self.pods)})"


class Hdfs:
    """Placeholder descriptor (the reference attaches HDFS checkpoint
    locations to the cluster; no HDFS client exists in this image)."""

    def __init__(self):
        self.hdfs_ugi = None
        self.hdfs_name = None
        self.hdfs_path = None

    def is_valid(self):
        return bool(self.hdfs_ugi and self.hdfs_name and self.hdfs_path)


class JobServer:
    def __init__(self):
        self.endpoint = None


def get_cluster(node_ips, node_ip, trainer_endpoints, device_ids):
    """Build the Cluster/Pod/Trainer descriptor tree (reference layout:
    one pod per node, one trainer per device group)."""
    cluster = Cluster()
    per_node = len(device_ids)
    rank = 0
    for node_rank, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = node_rank
        pod.addr = ip
        pod.id = node_rank
        eps = (trainer_endpoints[node_rank]
               if trainer_endpoints and isinstance(trainer_endpoints[0],
                                                   (list, tuple))
               else trainer_endpoints[node_rank * per_node:
                                      (node_rank + 1) * per_node])
        for i, dev in enumerate(device_ids):
            t = Trainer()
            t.gpus = list(dev) if isinstance(dev, (list, tuple)) else [dev]
            t.endpoint = eps[i] if i < len(eps) else None
            t.rank = rank
            rank += 1
            pod.trainers.append(t)
        cluster.pods.append(pod)
    return cluster, cluster.pods[node_ips.index(node_ip)]


def get_host_name_ip():
    try:
        name = socket.gethostname()
        return name, socket.gethostbyname(name)
    except OSError:
        return None


def find_free_ports(num):
    """num distinct currently-free TCP ports."""
    ports = set()
    socks = []
    try:
        while len(ports) < num:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind(("", 0))
            socks.append(s)         # hold open so ports stay distinct
            ports.add(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


def get_logger(log_level=20, name="root"):
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(levelname)s %(asctime)s %(message)s"))
        logger.addHandler(h)
    return logger


def add_arguments(argname, type, default, help, argparser, **kwargs):
    """ref utils.add_arguments — argparse helper used by launch scripts."""
    argparser.add_argument("--" + argname, default=default, type=type,
                           help=help + f" Default: {default}.", **kwargs)


class TrainerProc:
    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.log_offset = 0
        self.rank = None
        self.local_rank = None
        self.cmd = None


def start_local_trainers(cluster, pod, training_script,
                         training_script_args, log_dir=None, envs=None):
    """Spawn one process per trainer in this pod with the reference's env
    contract (PADDLE_TRAINER_ID / ENDPOINTS), jax.distributed-ready."""
    procs = []
    world = cluster.trainers_endpoints()
    for idx, t in enumerate(pod.trainers):
        env = dict(os.environ)
        env.update(envs or {})
        env.update({
            "PADDLE_TRAINER_ID": str(t.rank),
            "PADDLE_CURRENT_ENDPOINT": str(t.endpoint),
            "PADDLE_TRAINERS_NUM": str(cluster.trainers_nranks()),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(map(str, world)),
        })
        import sys
        cmd = [sys.executable, training_script] + list(training_script_args)
        tp = TrainerProc()
        tp.rank = t.rank
        tp.local_rank = idx
        tp.cmd = cmd
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            tp.log_fn = open(os.path.join(log_dir,
                                          f"workerlog.{idx}"), "w")
            tp.proc = subprocess.Popen(cmd, env=env, stdout=tp.log_fn,
                                       stderr=subprocess.STDOUT)
        else:
            tp.proc = subprocess.Popen(cmd, env=env)
        procs.append(tp)
    return procs


def watch_local_trainers(procs, nranks):
    """Poll; returns still-alive procs.  A nonzero exit terminates the
    sibling trainers and closes every log before raising — the caller
    never inherits orphans from a failed pod."""
    alive = []
    failed = None
    for tp in procs:
        ret = tp.proc.poll()
        if ret is None:
            alive.append(tp)
            continue
        if tp.log_fn and not tp.log_fn.closed:
            tp.log_fn.close()
        if ret != 0 and failed is None:
            failed = (tp, ret)
    if failed is not None:
        tp, ret = failed
        terminate_local_procs(alive)
        raise RuntimeError(
            f"trainer rank {tp.rank} exited with code {ret} "
            f"(cmd: {' '.join(tp.cmd)})")
    return alive


def terminate_local_procs(procs):
    for tp in procs:
        if tp.proc is not None and tp.proc.poll() is None:
            tp.proc.terminate()
    deadline = time.time() + 5
    for tp in procs:
        if tp.proc is None:
            continue
        try:
            tp.proc.wait(timeout=max(deadline - time.time(), 0.1))
        except subprocess.TimeoutExpired:
            tp.proc.send_signal(signal.SIGKILL)
            tp.proc.wait()          # reap — no zombies for the supervisor
        if tp.log_fn and not tp.log_fn.closed:
            tp.log_fn.close()


def pull_worker_log(tp):
    if not tp.log_fn:
        return
    with open(tp.log_fn.name) as f:
        f.seek(tp.log_offset)
        data = f.read()
        tp.log_offset = f.tell()
    if data:
        print(data, end="")
