"""paddle_tpu.distributed (ref: python/paddle/distributed/__init__.py)."""
from .parallel import (ParallelEnv, init_parallel_env, get_rank,
                       get_world_size, spawn, is_initialized)
from .collective import (ReduceOp, Group, new_group, get_group, barrier, wait,
                         all_reduce, reduce, all_gather, all_gather_object,
                         broadcast, scatter, alltoall, send, recv,
                         reduce_scatter, split, collective_axis,
                         CollectiveTimeout)
from . import fleet
from .data_parallel import DataParallel, DistributedDataParallel
from . import reducer
from .reducer import (Reducer, DeviceMeshAllReduce,  # noqa: F401
                      MeshAxesAllReduce, EagerProcessTransport)
from . import sharding
from .ps_compat import (EntryAttr, ProbabilityEntry,  # noqa: F401
                        CountFilterEntry, InMemoryDataset, QueueDataset)


def launch():
    from .launch import main
    main()
from . import utils  # noqa: E402


def __getattr__(name):
    # lazy (PEP 562): the model-parallel subsystem pulls the optimizer/
    # models layers — importing it eagerly here would lengthen (and risk
    # cycling) the base `import paddle_tpu.distributed`
    if name == "auto":
        import importlib
        mod = importlib.import_module(".auto", __name__)
        globals()["auto"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
