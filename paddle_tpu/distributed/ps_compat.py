"""Parameter-server era input/config surface, TPU-native.

ref: python/paddle/distributed/entry_attr.py (ProbabilityEntry,
CountFilterEntry), distributed/fleet/data_generator/data_generator.py
(DataGenerator / MultiSlot*), distributed/fleet/dataset/dataset.py
(InMemoryDataset, QueueDataset).

The reference feeds PS trainers from MultiSlot-format text streams
("<n> v1 .. vn" per slot, one sample per line) produced by DataGenerator
subclasses and consumed in C++ by MultiSlotDataFeed.  Here the SAME
protocol round-trips in Python/numpy: generators emit identical lines
(scripts and files port unchanged), datasets parse them into padded
[batch, max_len] arrays per slot (the fixed-shape TPU contract — ragged
feasign lists zero-pad to the batch max), and
``Executor.train_from_dataset`` iterates them as ordinary feeds.  The
async PS itself is deliberately absent (MIGRATING.md: synchronous-only);
these classes keep the era's data plumbing working on the sharded
embedding path."""
from __future__ import annotations

import os
import sys

import numpy as np


# ---------------------------------------------------------------- entries
class EntryAttr:
    """Sparse-row admission config for sparse_embedding (ref
    entry_attr.py:20).  On TPU the table is dense-sharded, so entries are
    carried as declarative metadata (accessible to tooling via _to_attr)
    rather than PS-server filters."""

    def __init__(self):
        self._name = None

    def _to_attr(self):
        raise NotImplementedError("EntryAttr is base class")


class ProbabilityEntry(EntryAttr):
    """Admit new sparse features with the given probability (ref :59)."""

    def __init__(self, probability):
        super().__init__()
        if not isinstance(probability, float):
            raise ValueError("probability must be a float in (0,1)")
        if probability <= 0 or probability >= 1:
            raise ValueError("probability must be a float in (0,1)")
        self._name = "probability_entry"
        self._probability = probability

    def _to_attr(self):
        return ":".join([self._name, str(self._probability)])


class CountFilterEntry(EntryAttr):
    """Admit a sparse feature after `count_filter` occurrences (ref :100)."""

    def __init__(self, count_filter):
        super().__init__()
        if not isinstance(count_filter, int):
            raise ValueError(
                "count_filter must be a valid integer greater than 0")
        if count_filter < 0:
            raise ValueError(
                "count_filter must be a valid integer greater or equal "
                "than 0")
        self._name = "count_filter_entry"
        self._count_filter = count_filter

    def _to_attr(self):
        return ":".join([self._name, str(self._count_filter)])


# ---------------------------------------------------------- data generator
class DataGenerator:
    """User subclasses override ``generate_sample(line)`` (returning a
    generator of [(slot, [feasign, ...]), ...]) and optionally
    ``generate_batch`` (ref data_generator.py:21).  run_from_stdin
    reproduces the reference's trainer-pipe protocol byte for byte."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        raise NotImplementedError(
            "generate_sample() must be overridden: return a generator "
            "yielding [(slot_name, [feasign, ...]), ...] per sample")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    def _run_samples(self, sample_iters):
        """Shared buffering core: accumulate parsed samples to batch_size_,
        flush each full batch (and the trailing partial one) through
        generate_batch -> _gen_str -> stdout."""
        batch_samples = []

        def _flush():
            batch_iter = self.generate_batch(batch_samples)
            for sample in batch_iter():
                sys.stdout.write(self._gen_str(sample))

        for line_iter in sample_iters:
            for user_parsed_line in line_iter():
                if user_parsed_line is None:
                    continue
                batch_samples.append(user_parsed_line)
                if len(batch_samples) == self.batch_size_:
                    _flush()
                    batch_samples = []
        if batch_samples:
            _flush()

    def run_from_stdin(self):
        """stdin lines -> protocol lines on stdout (the pipe_command
        contract)."""
        self._run_samples(self.generate_sample(line) for line in sys.stdin)

    def run_from_memory(self):
        """Debug path: generate without input lines, write to stdout."""
        self._run_samples([self.generate_sample(None)])


class MultiSlotStringDataGenerator(DataGenerator):
    """Slots carry pre-stringified feasigns (ref :239): output
    ``<n> s1 .. sn`` per slot."""

    def _gen_str(self, line):
        if isinstance(line, zip):
            line = list(line)
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type, "
                "e.g. [('words', ['1926', '08', '17']), ('label', ['1'])]")
        out = []
        for _name, elements in line:
            out.append(str(len(elements)))
            out.extend(str(e) for e in elements)
        return " ".join(out) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Slots carry int/float feasigns with a consistency-checked proto
    (ref :283): first sample fixes the field set and int/float kinds."""

    def _gen_str(self, line):
        if isinstance(line, zip):
            line = list(line)
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type, "
                "e.g. [('words', [1926, 8, 17]), ('label', [1])]")
        if self._proto_info is None:
            self._proto_info = []
            first = True
        else:
            first = False
            if len(line) != len(self._proto_info):
                raise ValueError("the complete field set of two given "
                                 "line are inconsistent.")
        out = []
        for index, (name, elements) in enumerate(line):
            if not isinstance(name, str):
                raise ValueError(f"name {type(name)} must be in str type")
            if not isinstance(elements, list):
                raise ValueError(
                    f"elements {type(elements)} must be in list type")
            if not elements:
                raise ValueError(
                    "the elements of each field can not be empty, you "
                    "need padding it in process().")
            if first:
                self._proto_info.append((name, "uint64"))
            elif name != self._proto_info[index][0]:
                raise ValueError(
                    "the field name of two given line are not match: "
                    f"require<{self._proto_info[index][0]}>, get<{name}>.")
            out.append(str(len(elements)))
            for elem in elements:
                if isinstance(elem, float):
                    self._proto_info[index] = (name, "float")
                elif not isinstance(elem, (int, np.integer)):
                    raise ValueError(
                        f"the type of element {type(elem)} must be in "
                        "int or float")
                out.append(str(elem))
        return " ".join(out) + "\n"


# ----------------------------------------------------------------- dataset
def _parse_multislot_line(line, n_slots):
    """One protocol line -> list of per-slot numpy value lists."""
    toks = line.split()
    slots, i = [], 0
    for _ in range(n_slots):
        if i >= len(toks):
            raise ValueError(f"truncated MultiSlot line: {line!r}")
        n = int(toks[i])
        vals = toks[i + 1:i + 1 + n]
        if len(vals) != n:
            raise ValueError(f"truncated MultiSlot line: {line!r}")
        slots.append(vals)
        i += 1 + n
    if i != len(toks):
        raise ValueError(
            f"MultiSlot line has {len(toks) - i} trailing token(s) beyond "
            f"the {n_slots} declared slots — slot count mismatch between "
            f"the data and dataset.init(use_var=...): {line!r}")
    return slots


class DatasetBase:
    """Common init/config of the reference's dataset family (ref
    dataset.py:38): slot vars, batch size, file list.  ``pipe_command``
    is honored by piping each file through it exactly like the trainer
    does (a DataGenerator script works unchanged); leave it empty to
    read files already in protocol format."""

    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._use_vars = []
        self._pipe_command = ""
        self._input_type = 0
        self._filelist = []
        self._pad_lens = {}    # slot idx -> stable padded length

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command="", input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self._batch_size = int(batch_size)
        self._thread_num = max(int(thread_num), 1)
        self._use_vars = list(use_var or [])
        self._pipe_command = pipe_command
        self._input_type = input_type
        return self

    # individual setters (the pre-2.0 spelling scripts use)
    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self._thread_num = max(int(thread_num), 1)

    def set_use_var(self, var_list):
        self._use_vars = list(var_list)

    def set_pipe_command(self, pipe_command):
        self._pipe_command = pipe_command

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def get_filelist(self):
        return list(self._filelist)

    # -- parsing ----------------------------------------------------------
    def _slot_dtypes(self):
        out = []
        for v in self._use_vars:
            d = np.dtype(getattr(v.value, "dtype", np.float32))
            out.append(np.int64 if d.kind in "iu" else np.float32)
        return out

    def _read_protocol_lines(self, path):
        if self._pipe_command:
            import subprocess
            with open(path, "rb") as f:
                proc = subprocess.run(
                    self._pipe_command, shell=True, stdin=f,
                    stdout=subprocess.PIPE, check=True)
            text = proc.stdout.decode()
        else:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
        return [ln for ln in text.splitlines() if ln.strip()]

    def _samples_from_files(self):
        n_slots = len(self._use_vars)
        if n_slots == 0:
            raise ValueError("dataset.init(use_var=...) must name the "
                             "slot variables before reading data")
        samples = []
        for path in self._filelist:
            for ln in self._read_protocol_lines(path):
                samples.append(_parse_multislot_line(ln, n_slots))
        return samples

    # ---- PS-era knobs (ref dataset.py): accepted for API shape; the
    # TPU pipeline has no distributed instance-id plumbing to configure
    def preprocess_instance(self):
        pass

    def postprocess_instance(self):
        pass

    def set_parse_ins_id(self, parse_ins_id):
        pass

    def set_parse_content(self, parse_content):
        pass

    def _init_distributed_settings(self, **kwargs):
        pass

    def update_settings(self, **kwargs):
        for k, v in kwargs.items():
            setattr(self, "_" + k, v)

    def set_queue_num(self, queue_num):
        self._queue_num = queue_num

    def set_fleet_send_batch_size(self, n=1024):
        pass

    def set_fleet_send_sleep_seconds(self, n=0):
        pass

    def set_merge_by_lineid(self, merge_size=2):
        pass

    def slots_shuffle(self, slots):
        pass

    def _slot_pad_len(self, si, batch_max):
        """Stable per-slot padded length.  Padding each batch to ITS max
        would hand the Executor a different feed shape per batch — one
        full XLA recompile each.  Lengths grow monotonically and round up
        to powers of two, so ragged data converges to a handful of
        shapes (InMemoryDataset pins the exact dataset max at load)."""
        cur = self._pad_lens.get(si, 0)
        if batch_max <= cur:
            return cur
        t = 1
        while t < batch_max:
            t *= 2
        self._pad_lens[si] = max(t, cur)
        return self._pad_lens[si]

    def _batches(self, samples):
        """Pad each slot to a stable length -> {name: [B, L] array}
        (the fixed-shape analogue of the reference's LoD batches)."""
        dtypes = self._slot_dtypes()
        names = [getattr(v, "name", f"slot_{i}")
                 for i, v in enumerate(self._use_vars)]
        bs = self._batch_size
        for start in range(0, len(samples), bs):
            chunk = samples[start:start + bs]
            if not chunk:
                continue
            feed = {}
            for si, (name, dt) in enumerate(zip(names, dtypes)):
                rows = [np.asarray(s[si], dt) for s in chunk]
                L = self._slot_pad_len(si, max(r.shape[0] for r in rows))
                arr = np.zeros((len(rows), L), dt)
                for ri, r in enumerate(rows):
                    arr[ri, :r.shape[0]] = r
                feed[name] = arr
            yield feed


class QueueDataset(DatasetBase):
    """Streaming dataset (ref dataset.py:1086): batches parse lazily per
    epoch, nothing is cached."""

    def iter_batches(self):
        n_slots = len(self._use_vars)
        if n_slots == 0:
            raise ValueError("dataset.init(use_var=...) must name the "
                             "slot variables before reading data")
        buf = []
        for path in self._filelist:
            for ln in self._read_protocol_lines(path):
                buf.append(_parse_multislot_line(ln, n_slots))
                if len(buf) >= self._batch_size:
                    yield from self._batches(buf[:self._batch_size])
                    buf = buf[self._batch_size:]
        if buf:
            yield from self._batches(buf)


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (ref dataset.py:253)."""

    def __init__(self):
        super().__init__()
        self._memory = []
        self._seed = None

    def load_into_memory(self):
        self._memory = self._samples_from_files()
        # the whole dataset is in hand: pin each slot's padded length to
        # the exact dataset-wide max so every batch shares ONE feed shape
        for si in range(len(self._use_vars)):
            if self._memory:
                self._pad_lens[si] = max(
                    len(np.asarray(s[si]).reshape(-1))
                    for s in self._memory)

    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def set_shuffle_by_uid(self, enable):
        pass

    def local_shuffle(self):
        rng = np.random.RandomState(self._seed)
        rng.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        # single-controller: global == local
        self.local_shuffle()

    def get_memory_data_size(self, fleet=None):
        return len(self._memory)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._memory)

    def release_memory(self):
        self._memory = []

    def slots_shuffle(self, slots):
        pass

    def iter_batches(self):
        if not self._memory:
            raise RuntimeError(
                "call load_into_memory() before iterating an "
                "InMemoryDataset")
        yield from self._batches(self._memory)
