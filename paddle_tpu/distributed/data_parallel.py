"""DataParallel wrapper (ref: python/paddle/fluid/dygraph/parallel.py).

The reference hooks NCCL allreduce onto gradient buckets.  Under the SPMD
model gradients are synced by the compiler: when the train step runs under
pjit with batch sharded over 'dp', grads of replicated params ARE the summed
grads.  Eager single-process training needs no sync.  In a MULTI-PROCESS
launch (jax.distributed initialized), grads are averaged across processes
through the overlap-scheduled bucketed reducer (distributed/reducer.py):
size-capped buckets in reverse registration order, each bucket's all_reduce
launched from the grad-ready hooks while backward is still walking earlier
layers, grad-less params contributing zeros at end-of-backward finalize.
The fluid-era explicit recipe (apply_collective_grads) and no_sync() keep
their semantics.

Knobs (see README "Pipelined data-parallel step"):
  bucket_size_mb   cap per gradient bucket (default: comm_buffer_size,
                   the reference's MB knob).  Smaller buckets overlap
                   earlier but launch more collectives.
  overlap          launch buckets from grad-ready hooks (True, default)
                   or all at end-of-backward in deterministic bucket
                   order (False — forced when find_unused_parameters,
                   where completion order may diverge across processes).
  mesh             a single-process jax Mesh: bucket reduction runs as
                   jitted psum collectives over its first axis instead of
                   host gathers.  This is the host-mesh bench/test
                   transport and the single-process-per-pod path.
"""
from __future__ import annotations

import contextlib
import weakref

from ..nn.layer.layers import Layer

# one live reducer per wrapped Layer: re-wrapping a model (checkpoint
# reload, notebook re-run) must detach the previous wrapper's hooks, or
# every backward would run TWO full bucket collective sequences
_reducer_of_layer: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, bucket_size_mb=None, overlap=True, mesh=None,
                 fuse_into_step=False):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self._sync_enabled = True
        self._group = group
        from . import collective
        self._collective = collective
        self.bucket_size_mb = (comm_buffer_size if bucket_size_mb is None
                               else bucket_size_mb)
        self._reducer = None
        from .reducer import (Reducer, DeviceMeshAllReduce,
                              EagerProcessTransport)
        if mesh is not None:
            transport = DeviceMeshAllReduce(mesh=mesh)
        elif collective._process_count() > 1:
            transport = EagerProcessTransport(group)
        else:
            transport = None          # world of one: grads are already global
        if transport is not None:
            prev = _reducer_of_layer.get(layers)
            if prev is not None:          # re-wrap: detach the old hooks
                prev.enabled = False
                prev.remove_hooks()
            # an unused param's hook never fires, so its bucket would
            # complete on SOME processes only — finalize-ordered launches
            # (overlap=False) keep the collective sequence deterministic
            # fuse_into_step=True keeps per-param .grad LOCAL and holds the
            # reduced flats for step_fused(optimizer) — opt in only when
            # the training loop uses step_fused, never plain opt.step()
            self._reducer = Reducer(
                self._layers.parameters(),
                bucket_size_mb=self.bucket_size_mb,
                transport=transport,
                overlap=overlap and not find_unused_parameters,
                fuse_into_step=fuse_into_step,
            ).install_hooks()
            _reducer_of_layer[layers] = self._reducer

    @property
    def reducer(self):
        return self._reducer

    def forward(self, *inputs, **kwargs):
        from ..observability import timeline as _timeline
        with _timeline.span("forward"):
            return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def step_fused(self, optimizer):
        """Pipelined update: feed the reduced flat buckets straight into
        the donated fused optimizer step — one jitted
        scale+unflatten+update, no per-param unbucketing round-trip.
        Falls back to ``optimizer.step()`` when nothing was reduced
        (world of one, no_sync, subset non-member)."""
        reduced = self._reducer.pop_reduced() if self._reducer else None
        if reduced is None:
            return optimizer.step()
        flats, layout, scale = reduced
        return optimizer.step_from_buckets(flats, layout, scale=scale)

    def apply_collective_grads(self):
        """Fluid-era explicit sync: average every param grad across
        processes in one flat gather (a no-op world of one)."""
        if (not self._sync_enabled
                or self._collective._process_count() <= 1):
            return
        from .fleet.utils import fused_allreduce_gradients
        fused_allreduce_gradients(
            [p for p in self._layers.parameters() if p is not None])

    @contextlib.contextmanager
    def no_sync(self):
        prev = self._sync_enabled
        self._sync_enabled = False
        if self._reducer is not None:
            self._reducer.enabled = False
        try:
            yield
        finally:
            self._sync_enabled = prev
            if self._reducer is not None:
                self._reducer.enabled = prev

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)


# the reference exports both names; the 2.x spelling carries the knobs
DistributedDataParallel = DataParallel
