"""DataParallel wrapper (ref: python/paddle/fluid/dygraph/parallel.py).

The reference hooks NCCL allreduce onto gradient buckets.  Under the SPMD
model gradients are synced by the compiler: when the train step runs under
pjit with batch sharded over 'dp', grads of replicated params ARE the summed
grads.  Eager single-process training needs no sync.  In a MULTI-PROCESS
launch (jax.distributed initialized), grads are averaged across processes:
automatically after each param's grad finalizes in backward (per-param
hooks, the reference reducer's semantics), batched through ONE flat
cross-process gather per backward via apply_collective_grads() when called
explicitly (the fluid-era recipe), with no_sync() suppressing both.
"""
from __future__ import annotations

import contextlib

from ..nn.layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self._sync_enabled = True
        self._group = group
        from . import collective
        self._collective = collective
        # per-param backward hooks require every process to reach every
        # param (static graphs) — the reference's default contract.  With
        # find_unused_parameters=True, auto-sync switches to the flat
        # all-params gather at apply_collective_grads() time instead
        # (grad-less params contribute zeros), because a hook that fires
        # on only SOME processes would desynchronize the collective
        # sequence and hang the job.
        if (collective._process_count() > 1
                and not find_unused_parameters):
            self._install_grad_sync_hooks()

    def _install_grad_sync_hooks(self):
        coll = self._collective

        def make_hook(p):
            def hook(g):
                if not self._sync_enabled:
                    return None
                member, rows = coll._member_rows(
                    coll._eager_rows(g.numpy()), self._group)
                if not member:
                    return None
                from ..tensor.tensor import Tensor
                return Tensor(rows.mean(0))
            return hook

        for p in self._layers.parameters():
            if p is not None and not p.stop_gradient:
                p.register_hook(make_hook(p))

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        """Fluid-era explicit sync: average every param grad across
        processes in one flat gather (a no-op world of one)."""
        if (not self._sync_enabled
                or self._collective._process_count() <= 1):
            return
        from .fleet.utils import fused_allreduce_gradients
        fused_allreduce_gradients(
            [p for p in self._layers.parameters() if p is not None])

    @contextlib.contextmanager
    def no_sync(self):
        prev = self._sync_enabled
        self._sync_enabled = False
        try:
            yield
        finally:
            self._sync_enabled = prev

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)
