"""DataParallel wrapper (ref: python/paddle/fluid/dygraph/parallel.py).

The reference hooks NCCL allreduce onto gradient buckets.  Under the SPMD
model gradients are synced by the compiler: when the train step runs under
pjit with batch sharded over 'dp', grads of replicated params ARE the summed
grads.  Eager single-process training needs no sync at all, so this wrapper
is semantically transparent while keeping the reference API (scale_loss,
no_sync, state_dict passthrough).
"""
from __future__ import annotations

from ..nn.layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    import contextlib

    @contextlib.contextmanager
    def no_sync(self):
        yield

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)
