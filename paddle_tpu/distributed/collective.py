"""Collective ops (ref: python/paddle/distributed/collective.py →
paddle/fluid/operators/collective/c_allreduce_op.h etc.).

TPU-native: inside a mapped region (shard_map / fleet parallel step) each op
lowers to the XLA collective (psum / all_gather / ppermute / all_to_all)
over the named mesh axis, riding ICI.  Outside a mapped region there are
two cases: a single-process world, where they are identities; and a
multi-process launch (``jax.distributed`` initialized — the reference's
gloo control-plane case), where they aggregate host values across
processes via ``jax.experimental.multihost_utils``.  The eager cross-
process path is control-plane machinery (metrics, LocalSGD parameter
averaging, file sharding); the data plane stays inside mapped regions.

Subset-``group`` eager collectives still require EVERY live process to
make the call (the underlying gather is global); only member rows enter
the reduction and non-members get their input back.  send/recv keep the
single-process buffer emulation — a true cross-process p2p pair would
deadlock a global collective, matching the reference's restriction of
gloo send/recv to in-graph ops.

The active axis name is provided by the surrounding parallel context
(fleet sets it when entering tensor/data-parallel regions).
"""
from __future__ import annotations

import contextlib
import os
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.dispatch import call
from ..tensor.tensor import Tensor
from ..testing import faults as _faults


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    def __init__(self, rank, nranks, id=0, ranks=None, axis_name=None):
        self.rank = rank
        self.nranks = nranks
        self.id = id
        self.ranks = ranks or list(range(nranks))
        self.axis_name = axis_name  # mesh axis this group reduces over

    def is_member(self):
        return self.rank >= 0

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def world_size(self):
        return self.nranks

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(rank={self.rank}, nranks={self.nranks}, axis={self.axis_name})"


_group_map = {}
_default_group = None

# axis-name stack installed by parallel contexts (shard_map bodies)
_axis_stack = []


@contextlib.contextmanager
def collective_axis(axis_name):
    """Install the mesh axis that collectives should reduce over; used by
    fleet/shard_map wrappers around parallel step functions."""
    _axis_stack.append(axis_name)
    try:
        yield
    finally:
        _axis_stack.pop()


def _current_axis(group=None):
    if group is not None and group.axis_name:
        return group.axis_name
    return _axis_stack[-1] if _axis_stack else None


def _process_count():
    try:
        return jax.process_count()
    except Exception:                                      # noqa: BLE001
        return 1


_kv_seq = [0]
_KV_TIMEOUT_MS = 60_000


class CollectiveTimeout(RuntimeError):
    """A rendezvous/transport wait expired: some rank never showed up.
    Carries everything the operator needs to find the dead rank — the op,
    the group, the bucket (for reducer collectives), and which ranks DID
    contribute before the deadline."""

    def __init__(self, op, timeout_ms, group=None, bucket=None,
                 ranks_seen=None, nranks=None, detail=""):
        self.op = op
        self.group = group
        self.bucket = bucket
        self.ranks_seen = ranks_seen
        msg = f"collective '{op}' timed out after {timeout_ms}ms"
        if bucket is not None:
            msg += f" (bucket {bucket})"
        msg += f" in group {group if group is not None else 'WORLD'}"
        if ranks_seen is not None and nranks:
            missing = sorted(set(range(nranks)) - set(ranks_seen))
            msg += (f"; ranks seen before the deadline: "
                    f"{sorted(ranks_seen)} of {nranks} — missing "
                    f"{missing}: those processes are hung or dead")
        if detail:
            msg += f" ({detail})"
        msg += ("; tune PADDLE_COLLECTIVE_TIMEOUT (seconds) for slow "
                "interconnects")
        super().__init__(msg)


# watchdog counters, surfaced through profiler.fast_path_summary(); a
# VIEW over the observability registry's "watchdog" family (same storage)
from ..observability import metrics as _metrics
from ..observability import timeline as _timeline

_watchdog_stats = _metrics.stats_family("watchdog", {
    "collective_timeouts": 0,   # waits that expired into CollectiveTimeout
    "kv_retries": 0,            # transient KV-store op failures absorbed
})


def watchdog_stats():
    return dict(_watchdog_stats)


def reset_watchdog_stats():
    for k in _watchdog_stats:
        _watchdog_stats[k] = 0


def _collective_timeout_ms():
    """Configurable rendezvous deadline (PADDLE_COLLECTIVE_TIMEOUT,
    seconds; default 60).  Read per call so operators and tests can tune
    a live process."""
    try:
        return max(int(float(os.environ.get(
            "PADDLE_COLLECTIVE_TIMEOUT", "60")) * 1000), 1)
    except ValueError:
        return _KV_TIMEOUT_MS


def _is_deadline(err):
    msg = str(err).lower()
    return "deadline" in msg or "timed out" in msg or "timeout" in msg


def _is_transient(err):
    """Coordinator-hiccup-shaped failures worth retrying.  Anything else
    (AttributeError on a missing client, pickling bugs, ...) is a real
    error that retrying would only mask.  Narrower than
    _dist_bootstrap._transient on purpose: mid-training deadlines are
    watchdog events (CollectiveTimeout), never retries, while at
    bootstrap a deadline just means peers have not arrived yet."""
    if isinstance(err, _faults.InjectedFault):
        return True
    msg = str(err).lower()
    return any(s in msg for s in (
        "unavailable", "connection", "reset", "broken pipe", "aborted",
        "internal", "try again"))


def _kv_call(client, method, *args):
    """One KV-store/coordination-service op with bounded retry-with-
    backoff on transient failures (the coordinator riding a restarting
    pod emits UNAVAILABLE-shaped errors that resolve in milliseconds).
    Deadline expiries are NOT retried — the caller turns them into a
    diagnosable CollectiveTimeout — and neither are non-transient
    errors.  A transient error that survives every retry is re-raised
    as-is; rendezvous call sites (:func:`_kv_allgather`, :func:`barrier`)
    convert THAT into a CollectiveTimeout too (op/group/ranks named)
    rather than surfacing a bare KV error mid-collective."""
    retries = int(os.environ.get("PADDLE_KV_RETRIES", "3"))
    delay = 0.05
    for attempt in range(retries + 1):
        try:
            _faults.kv_fault(method)       # deterministic injection point
            return getattr(client, method)(*args)
        except Exception as e:                             # noqa: BLE001
            if _is_deadline(e) or not _is_transient(e) \
                    or attempt >= retries:
                raise
            _watchdog_stats["kv_retries"] += 1
            time.sleep(delay)
            delay *= 2


def _watchdog_detail(e):
    """(convert?, detail) for an exception escaping a rendezvous _kv_call:
    deadlines and retry-exhausted transients both become
    CollectiveTimeout — the group is equally broken either way, and the
    operator needs op/group/ranks, not a bare KV stack."""
    if _is_deadline(e):
        return True, str(e).splitlines()[0]
    if _is_transient(e):
        return True, ("PADDLE_KV_RETRIES exhausted on a transient "
                      "coordinator failure: " + str(e).splitlines()[0])
    return False, None


def _kv_world():
    """(client, process_count, process_index) — one seam for the
    watchdog unit tests to stand in a fake coordination service."""
    from jax._src import distributed
    return distributed.global_state.client, jax.process_count(), \
        jax.process_index()


def _ranks_seen(client, key, n, budget_s=5.0):
    """Post-timeout forensics: which ranks' contributions exist in the
    store?  Direct client calls (no retry backoff) with a tiny per-key
    deadline AND a total time budget — on a big pod the diagnosis must
    cost seconds, not minutes; ranks not probed before the budget ran
    out simply don't appear."""
    seen = []
    deadline = time.monotonic() + budget_s
    for j in range(n):
        if time.monotonic() > deadline:
            break
        try:
            client.blocking_key_value_get(f"{key}/{j}", 200)
            seen.append(j)
        except Exception:                                  # noqa: BLE001
            pass
    return seen


def _kv_allgather(value, op="allgather", bucket=None, group=None):
    """Host allgather over the jax.distributed coordination service's
    key-value store — no XLA collective involved, so it works on backends
    whose device collectives can't span processes (CPU).  Strictly
    control-plane: payloads ride the coordinator, so keep them small.

    Watchdog: every wait is bounded by PADDLE_COLLECTIVE_TIMEOUT; an
    expired rendezvous raises CollectiveTimeout naming the op, group,
    bucket, and the ranks whose contributions DID arrive, instead of
    hanging the training loop forever."""
    import base64
    import pickle
    client, n, me = _kv_world()
    timeout_ms = _collective_timeout_ms()
    _kv_seq[0] += 1
    key = f"paddle_tpu_eager_ag_{_kv_seq[0]}"
    if _faults.active():
        _faults.collective_entry(op)       # injected straggler/vanish
    payload = base64.b64encode(
        pickle.dumps(np.asarray(value))).decode("ascii")
    _kv_call(client, "key_value_set", f"{key}/{me}", payload)
    # rendezvous wait, measured AFTER this rank contributed: a straggler
    # (slow producer) records ~zero here while its peers record the time
    # they sat at the barrier — the asymmetry the telemetry aggregator's
    # straggler detector keys on (observability/aggregate.py)
    t_wait = time.perf_counter()
    try:
        _kv_call(client, "wait_at_barrier", f"{key}_barrier", timeout_ms)
        rows = [pickle.loads(base64.b64decode(_kv_call(
            client, "blocking_key_value_get", f"{key}/{j}", timeout_ms))) for j in range(n)]
        _timeline.record_collective_wait(
            time.perf_counter() - t_wait, op=op)
    except Exception as e:                                 # noqa: BLE001
        convert, detail = _watchdog_detail(e)
        if not convert:
            raise
        _watchdog_stats["collective_timeouts"] += 1
        raise CollectiveTimeout(
            op, timeout_ms, group=group, bucket=bucket,
            ranks_seen=_ranks_seen(client, key, n), nranks=n,
            detail=detail) from e
    # everyone has read every row — each process reclaims its own key so
    # per-step collectives don't grow the coordinator's store unboundedly
    try:
        _kv_call(client, "wait_at_barrier", f"{key}_drain", timeout_ms)
    except Exception as e:                                 # noqa: BLE001
        convert, detail = _watchdog_detail(e)
        if not convert:
            raise
        # a peer vanished AFTER contributing: the gather completed but
        # the group is broken — same diagnosable failure, named as such
        _watchdog_stats["collective_timeouts"] += 1
        raise CollectiveTimeout(
            op, timeout_ms, group=group, bucket=bucket,
            ranks_seen=_ranks_seen(client, key, n), nranks=n,
            detail="post-gather drain barrier: " + detail) from e
    try:
        client.key_value_delete(f"{key}/{me}")
    except Exception:                                      # noqa: BLE001
        pass                       # older client without delete: best effort
    return np.stack(rows)


def _eager_rows(value, op="allgather", bucket=None, group=None):
    """Host-level cross-process allgather: every live process contributes
    its local value; returns a [process_count, ...] numpy stack."""
    from jax.experimental import multihost_utils
    try:
        return np.asarray(
            multihost_utils.process_allgather(np.asarray(value)))
    except CollectiveTimeout:
        raise
    except Exception:                                      # noqa: BLE001
        # e.g. "Multiprocess computations aren't implemented on the CPU
        # backend" — gather through the coordination service instead
        return _kv_allgather(value, op=op, bucket=bucket, group=group)


def _member_rows(rows, group):
    """(member?, member rows) for a possibly-subset group."""
    if (group is not None and group.ranks
            and len(group.ranks) < rows.shape[0]):
        return group.rank >= 0, rows[np.asarray(group.ranks)]
    return True, rows


def _adopt(tensor, value):
    """Rebind ``tensor`` to a host value, preserving trainability (a bare
    Tensor defaults to stop_gradient=True — adopting that would silently
    freeze a Parameter)."""
    sg = tensor.stop_gradient
    tensor._rebind(Tensor(value))
    tensor.stop_gradient = sg
    return tensor


def _get_global_group():
    global _default_group
    if _default_group is None:
        from .parallel import get_rank, get_world_size
        _default_group = Group(get_rank(), max(get_world_size(), 1), 0)
    return _default_group


def get_group(gid=0):
    if gid == 0:
        return _get_global_group()
    return _group_map.get(gid)


def new_group(ranks=None, backend=None, axis_name=None):
    from .parallel import get_rank
    gid = len(_group_map) + 1
    ranks = ranks or []
    me = get_rank()
    rank = ranks.index(me) if me in ranks else (0 if not ranks else -1)
    g = Group(rank, max(len(ranks), 1), gid, ranks, axis_name)
    _group_map[gid] = g
    return g


_barrier_counter = [0]


def barrier(group=None):
    if _process_count() > 1:
        from jax.experimental import multihost_utils
        _barrier_counter[0] += 1
        name = f"paddle_tpu_barrier_{_barrier_counter[0]}"
        timeout_ms = _collective_timeout_ms()
        try:
            multihost_utils.sync_global_devices(name)
        except Exception:                                  # noqa: BLE001
            # CPU backend: no cross-process device collectives — use the
            # coordination service barrier directly (watchdog-bounded)
            client, n, _ = _kv_world()
            try:
                _kv_call(client, "wait_at_barrier", name, timeout_ms)
            except Exception as e:                         # noqa: BLE001
                convert, detail = _watchdog_detail(e)
                if not convert:
                    raise
                _watchdog_stats["collective_timeouts"] += 1
                raise CollectiveTimeout(
                    "barrier", timeout_ms, group=group, nranks=n,
                    detail=detail) from e
        return
    jnp.zeros(()).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and hasattr(tensor.value,
                                              "block_until_ready"):
        tensor.value.block_until_ready()


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    ax = _current_axis(group)
    if ax is None:
        if _process_count() > 1:
            member, rows = _member_rows(_eager_rows(
                tensor.numpy(), op="all_reduce", group=group), group)
            if not member:
                return tensor
            red = {ReduceOp.SUM: rows.sum(0), ReduceOp.MAX: rows.max(0),
                   ReduceOp.MIN: rows.min(0), ReduceOp.PROD: rows.prod(0),
                   ReduceOp.AVG: rows.mean(0)}[op]
            _adopt(tensor, red.astype(rows.dtype))
            return tensor
        return tensor  # world of one: identity

    def _ar(x):
        if op == ReduceOp.SUM:
            return jax.lax.psum(x, ax)
        if op == ReduceOp.MAX:
            return jax.lax.pmax(x, ax)
        if op == ReduceOp.MIN:
            return jax.lax.pmin(x, ax)
        if op == ReduceOp.AVG:
            return jax.lax.pmean(x, ax)
        if op == ReduceOp.PROD:
            return jnp.exp(jax.lax.psum(jnp.log(x), ax))
        raise ValueError(op)
    out = call(_ar, tensor, _name="c_allreduce")
    tensor._rebind(out)
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # XLA collectives are symmetric; reduce == all_reduce with only dst using
    # the value (the compiler DCEs unused outputs elsewhere)
    return all_reduce(tensor, op, group, sync_op)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    ax = _current_axis(group)
    if ax is None:
        if _process_count() > 1:
            member, rows = _member_rows(_eager_rows(
                tensor.numpy(), op="all_gather", group=group), group)
            if member:
                tensor_list.extend(Tensor(r) for r in rows)
            return tensor_list
        tensor_list.append(tensor.clone())
        return tensor_list

    def _ag(x):
        return jax.lax.all_gather(x, ax)
    gathered = call(_ag, tensor, _name="c_allgather")
    n = gathered.shape[0]
    from ..tensor.manipulation import unstack
    tensor_list.extend(unstack(gathered, axis=0, num=n))
    return tensor_list


def all_gather_object(obj_list, obj, group=None):
    if _process_count() > 1:
        import pickle
        buf = np.frombuffer(pickle.dumps(obj), np.uint8)
        # two rounds: agree on the max payload size, then gather padded
        sizes = _eager_rows(np.asarray([buf.size], np.int64),
                            op="all_gather_object", group=group)[:, 0]
        padded = np.zeros(int(sizes.max()), np.uint8)
        padded[:buf.size] = buf
        rows = _eager_rows(padded, op="all_gather_object", group=group)
        member, rows = _member_rows(rows, group)
        if member:
            msizes = _member_rows(sizes[:, None], group)[1][:, 0]
            obj_list.extend(pickle.loads(r[:int(n)].tobytes())
                            for r, n in zip(rows, msizes))
        return obj_list
    obj_list.append(obj)
    return obj_list


def broadcast(tensor, src=0, group=None, sync_op=True):
    ax = _current_axis(group)
    if ax is None:
        if _process_count() > 1:
            # src is a GLOBAL rank (reference semantics): gather
            # unfiltered; only group MEMBERS adopt src's row
            rows = _eager_rows(tensor.numpy(), op="broadcast",
                               group=group)
            if group is None or not group.ranks \
                    or len(group.ranks) >= rows.shape[0] \
                    or group.rank >= 0:
                _adopt(tensor, rows[src])
            return tensor
        return tensor

    def _bc(x):
        # take src's value on every member: gather then index
        return jax.lax.all_gather(x, ax)[src]
    out = call(_bc, tensor, _name="c_broadcast")
    tensor._rebind(out)
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    ax = _current_axis(group)
    if ax is None:
        if _process_count() > 1:
            # every process must contribute the SAME shape: n_slots is
            # the group size (the number of scatter destinations), and
            # each member's slot is its group rank
            n_slots = (len(group.ranks)
                       if group is not None and group.ranks
                       and len(group.ranks) < _process_count()
                       else _process_count())
            me = jax.process_index()
            if (group is not None and group.ranks
                    and len(group.ranks) < _process_count()):
                if group.rank < 0:
                    _eager_rows(np.zeros(
                        (n_slots,) + tuple(np.asarray(
                            tensor.numpy()).shape),
                        np.asarray(tensor.numpy()).dtype))
                    return tensor     # non-member: participate, no adopt
                me = group.rank
            if tensor_list:
                local = np.stack([np.asarray(t.numpy())
                                  for t in tensor_list])
            else:
                local = np.zeros(
                    (n_slots,) + tuple(np.asarray(tensor.numpy()).shape),
                    np.asarray(tensor.numpy()).dtype)
            rows = _eager_rows(local, op="scatter",
                               group=group)   # [nproc, n_slots, ...]
            _adopt(tensor, rows[src, me])
            return tensor
        if tensor_list:
            tensor._rebind(tensor_list[0].clone())
        return tensor
    from ..tensor.manipulation import stack

    def _sc(stacked):
        idx = jax.lax.axis_index(ax)
        return jnp.take(jax.lax.all_gather(stacked, ax)[src], idx, axis=0)
    out = call(_sc, stack(tensor_list, 0), _name="c_scatter")
    tensor._rebind(out)
    return tensor


def alltoall(in_tensor_list, out_tensor_list, group=None, sync_op=True):
    ax = _current_axis(group)
    if ax is None:
        if _process_count() > 1:
            # subset groups map through group ranks exactly like scatter:
            # slots are GROUP ranks, non-members feed the global gather a
            # zero payload and adopt nothing
            subset = (group is not None and group.ranks
                      and len(group.ranks) < _process_count())
            if subset:
                n_slots = len(group.ranks)
                if group.rank < 0:
                    sample = np.asarray(in_tensor_list[0].numpy())
                    _eager_rows(np.zeros((n_slots,) + sample.shape,
                                         sample.dtype))
                    return out_tensor_list  # non-member: participate only
                me = group.rank
            else:
                me = jax.process_index()
            local = np.stack([np.asarray(t.numpy())
                              for t in in_tensor_list])
            rows = _eager_rows(local, op="alltoall",
                               group=group)   # [nproc, n_slots, ...]
            member, rows = _member_rows(rows, group)
            # group-member j's slot-`me` entry is my j-th output
            out_tensor_list.extend(Tensor(rows[j, me])
                                   for j in range(rows.shape[0]))
            return out_tensor_list
        out_tensor_list.extend(t.clone() for t in in_tensor_list)
        return out_tensor_list
    from ..tensor.manipulation import stack, unstack

    def _a2a(x):
        return jax.lax.all_to_all(x, ax, split_axis=0, concat_axis=0,
                                  tiled=False)
    stacked = stack(in_tensor_list, 0)
    out = call(_a2a, stacked, _name="c_alltoall")
    out_tensor_list.extend(unstack(out, axis=0, num=len(in_tensor_list)))
    return out_tensor_list


def send(tensor, dst=0, group=None, sync_op=True):
    ax = _current_axis(group)
    if ax is None:
        _p2p_buf.append(tensor.clone())
        return

    # a single-program SPMD region cannot express "whoever calls send
    # owns the payload" — a ppermute with every source targeting dst is
    # an invalid collective (duplicate destinations).  Point-to-point
    # inside mapped code is spelled as an explicit shift/permutation
    # (jax.lax.ppermute), which the pipeline/ring APIs already use.
    raise NotImplementedError(
        "send() inside a mapped region has no SPMD meaning; use "
        "jax.lax.ppermute with an explicit (src, dst) permutation (see "
        "parallel/pipeline.py) or the eager cross-process collectives")


_p2p_buf = []


def recv(tensor, src=0, group=None, sync_op=True):
    ax = _current_axis(group)
    if ax is None:
        if _p2p_buf:
            tensor._rebind(_p2p_buf.pop(0))
        return tensor

    raise NotImplementedError(
        "recv() inside a mapped region has no SPMD meaning; use "
        "jax.lax.ppermute with an explicit (src, dst) permutation (see "
        "parallel/pipeline.py) or the eager cross-process collectives")


def _c_identity(tensor, group=None):
    return tensor


def _c_concat(tensor, group=None):
    ax = _current_axis(group)
    if ax is None:
        return tensor

    def _cc(x):
        return jax.lax.all_gather(x, ax, axis=x.ndim - 1, tiled=True)
    return call(_cc, tensor, _name="c_concat")


def _c_split(tensor, group=None):
    ax = _current_axis(group)
    if ax is None:
        return tensor

    def _cs(x):
        idx = jax.lax.axis_index(ax)
        from ..framework.jax_compat import axis_size
        n = axis_size(ax)
        sz = x.shape[-1] // n
        return jax.lax.dynamic_slice_in_dim(x, idx * sz, sz, axis=x.ndim - 1)
    return call(_cs, tensor, _name="c_split")


def _mp_allreduce(tensor, group=None):
    return all_reduce(tensor, ReduceOp.SUM, group)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """With ``tensor_list`` (the reference contract), each rank
    contributes its list and receives the reduction of everyone's
    rank-th entry into ``tensor``; without it, ``tensor`` itself is
    reduced and scattered along axis 0."""
    from ..tensor.manipulation import stack
    src = stack(tensor_list, 0) if tensor_list else tensor
    ax = _current_axis(group)
    if ax is None:
        if _process_count() > 1:
            member, rows = _member_rows(_eager_rows(
                src.numpy(), op="reduce_scatter", group=group), group)
            if member:
                red = {ReduceOp.SUM: rows.sum(0),
                       ReduceOp.AVG: rows.mean(0),
                       ReduceOp.MAX: rows.max(0),
                       ReduceOp.MIN: rows.min(0),
                       ReduceOp.PROD: rows.prod(0)}[op]
                n = rows.shape[0]
                me = jax.process_index()
                if group is not None and group.ranks and n < _process_count():
                    me = group.rank           # subset group: scatter by
                if tensor_list:               # group rank, not global
                    _adopt(tensor, red[me])   # slot per rank, no extra dim
                else:
                    sz = red.shape[0] // n
                    _adopt(tensor, red[me * sz:(me + 1) * sz])
            return tensor
        if tensor_list:
            _adopt(tensor, src.numpy()[0])    # world of one: first slot
        return tensor

    def _rs(x):
        from ..framework.jax_compat import psum_scatter
        return psum_scatter(x, ax, scatter_dimension=0,
                            tiled=not bool(tensor_list))
    out = call(_rs, src, _name="c_reduce_scatter")
    tensor._rebind(out)
    return tensor


def split(x, num_or_sections, axis=0):
    from ..tensor.manipulation import split as _split
    return _split(x, num_or_sections, axis)
