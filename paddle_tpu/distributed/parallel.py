"""Parallel environment (ref: python/paddle/distributed/parallel.py).

The reference is multi-process NCCL (one proc per GPU).  TPU-native model is
single-controller SPMD: one python process drives all chips through a
jax.sharding.Mesh, and "rank"/"world size" describe positions in that mesh.
Multi-host uses jax.distributed.initialize (one controller per host, ICI/DCN
underneath) — see launch.py.
"""
from __future__ import annotations

import os

import jax


class ParallelEnv:
    def __init__(self):
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID",
                                       jax.process_index()))
        self.world_size = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                             jax.process_count()))
        self.device_id = 0
        self.current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT",
                                               "127.0.0.1:6170")
        self.trainer_endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS",
                                                self.current_endpoint
                                                ).split(",")

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank

    @property
    def dev_id(self):
        return self.device_id


_env = None
_initialized = False


def init_parallel_env():
    """Initialize SPMD environment.  For multi-host pods set
    PADDLE_MASTER/PADDLE_TRAINERS_NUM and this calls
    jax.distributed.initialize; single host is a no-op beyond env setup."""
    global _env, _initialized
    if _initialized:
        return _env
    master = os.environ.get("PADDLE_MASTER")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if master and nprocs > 1 and jax.process_count() == 1:
        jax.distributed.initialize(
            coordinator_address=master,
            num_processes=nprocs,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    _env = ParallelEnv()
    _initialized = True
    return _env


def get_rank(group=None):
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return jax.process_count()


def is_initialized():
    return _initialized


def parallel_helper_env():
    return _env


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """ref: python/paddle/distributed/spawn.py.  Under the SPMD model the
    single controller already drives every chip, so spawn degenerates to one
    invocation (parity shim for scripts written against the proc-per-GPU
    model)."""
    init_parallel_env()
    result = func(*args)
    return result
