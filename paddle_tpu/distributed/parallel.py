"""Parallel environment (ref: python/paddle/distributed/parallel.py).

The reference is multi-process NCCL (one proc per GPU).  TPU-native model is
single-controller SPMD: one python process drives all chips through a
jax.sharding.Mesh, and "rank"/"world size" describe positions in that mesh.
Multi-host uses jax.distributed.initialize (one controller per host, ICI/DCN
underneath) — see launch.py.
"""
from __future__ import annotations

import os

import jax


class ParallelEnv:
    def __init__(self):
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID",
                                       jax.process_index()))
        self.world_size = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                             jax.process_count()))
        self.device_id = 0
        self.current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT",
                                               "127.0.0.1:6170")
        self.trainer_endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS",
                                                self.current_endpoint
                                                ).split(",")

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank

    @property
    def dev_id(self):
        return self.device_id


_env = None
_initialized = False


def init_parallel_env():
    """Initialize SPMD environment.  For multi-host pods set
    PADDLE_MASTER/PADDLE_TRAINERS_NUM (the launcher does) and the shared
    bootstrap connects jax.distributed; single host is a no-op beyond env
    setup.  The bootstrap normally already fired at ``import paddle_tpu``
    — this call covers direct users who set the env afterwards (it must
    then run before any other jax use, or it raises with guidance)."""
    global _env, _initialized
    if _initialized:
        return _env
    from .._dist_bootstrap import maybe_init_distributed
    maybe_init_distributed()
    _env = ParallelEnv()
    _initialized = True
    return _env


def get_rank(group=None):
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return jax.process_count()


def is_initialized():
    return _initialized


def parallel_helper_env():
    return _env


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """ref: python/paddle/distributed/spawn.py.

    Under the SPMD model one controller already drives every local chip,
    so ``nprocs in (-1, 0, 1)`` runs ``func`` in-process (the TPU-correct
    mode).  ``nprocs > 1`` really forks worker processes (multiprocessing
    'spawn' context, rank in PADDLE_TRAINER_ID) for scripts written
    against the reference's proc-per-device model — intended for CPU
    testing; on real TPU hosts multiple processes cannot share the chip.
    """
    if nprocs is None or nprocs <= 1:
        init_parallel_env()
        return func(*args)

    import multiprocessing as mp
    ctx = mp.get_context("spawn")
    procs = []
    # children must see their per-rank env AT IMPORT (the package-level
    # coordinator bootstrap fires then); workers are local-only, so the
    # parent's coordinator env must not leak into them
    saved = {k: os.environ.get(k) for k in
             ("PADDLE_MASTER", "PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM")}
    try:
        os.environ.pop("PADDLE_MASTER", None)
        os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
        for rank in range(nprocs):
            os.environ["PADDLE_TRAINER_ID"] = str(rank)
            p = ctx.Process(target=_spawn_worker,
                            args=(func, args, rank, nprocs), daemon=daemon)
            p.start()
            procs.append(p)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    if not join:
        return procs
    failed = []
    for p in procs:
        p.join()
        if p.exitcode != 0:
            failed.append(p.exitcode)
    if failed:
        raise RuntimeError(f"spawn: {len(failed)} worker(s) failed with "
                           f"exit codes {failed}")
    return None


def _spawn_worker(func, args, rank, nprocs):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    init_parallel_env()
    func(*args)
