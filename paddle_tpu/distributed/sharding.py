"""Sharded (ZeRO) training — the eager placement API (ref:
python/paddle/distributed/sharding/ + fleet sharding meta-optimizer).

This module serves the dygraph ``group_sharded_parallel`` surface: it
PLACES existing eager state with dp-sharded NamedShardings and lets GSPMD
insert collectives per-op.  The real compiled ZeRO — explicit
reduce-scatter of grads into the sharded moment layout, gather-on-use FSDP
with sub-axis (flattened+padded) sharding so every leaf shards regardless
of axis divisibility, all inside ONE jitted shard_map step — lives in
``paddle_tpu.parallel.zero`` (make_zero_train_step / init_zero_state);
use that for training loops, as fleet's static path does.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_mod


def _dp_spec(shape, dp_size):
    """Shard the largest dp-divisible axis over 'dp'; replicated if none."""
    if not shape:
        return P()
    cands = [i for i in range(len(shape)) if shape[i] % dp_size == 0]
    if not cands:
        return P()
    axis = max(cands, key=lambda i: shape[i])
    spec = [None] * len(shape)
    spec[axis] = "dp"
    return P(*spec)


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2**23, segment_size=2**20,
                           sync_comm=False):
    """level: 'os' (stage1: optimizer states), 'os_g' (stage2: +grads),
    'p_g_os' (stage3: +params).  Requires an active mesh with a 'dp' axis
    (parallel.mesh.set_mesh / mesh_scope)."""
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    optimizer._zero_stage = stage

    mesh = mesh_mod.get_mesh()
    if mesh is not None and "dp" in mesh.axis_names:
        dp = dict(zip(mesh.axis_names, mesh.devices.shape))["dp"]
        if dp > 1:
            # stage>=1: moments live dp-sharded; the optimizer asks us how
            # to place each accumulator it creates
            def place_accumulator(p, zeros):
                ns = NamedSharding(mesh, _dp_spec(zeros.shape, dp))
                return jax.device_put(zeros, ns)

            optimizer._accumulator_placement = place_accumulator
            # re-place any accumulators that already exist
            by_id = {id(p): p for p in optimizer._parameters}
            for nm, d in optimizer._accumulators.items():
                for pid, arr in list(d.items()):
                    if pid in by_id:
                        d[pid] = place_accumulator(by_id[pid], arr)
            if stage >= 3:
                for p in model.parameters():
                    spec = _dp_spec(p.shape, dp)
                    p._sharding_axes = tuple(spec)
                mesh_mod.shard_params(model)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    from ..io.serialization import save
    save(model.state_dict(), output + ".pdmodel.params")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
