"""DEPRECATED shim — the eager ZeRO placement API now lives in
``paddle_tpu.distributed.auto.zero`` (ISSUE 10 folded this module into
the model-parallel subsystem; see MIGRATING.md "fluid fleet -> mesh").

``group_sharded_parallel``/``save_group_sharded_model`` keep their exact
signatures and semantics as thin aliases with a one-time
DeprecationWarning: placement-only ZeRO over the active mesh's 'dp'
axis, the donated fused optimizer step keeping moments sharded across
updates.  New code should call
:func:`paddle_tpu.distributed.auto.zero.shard_optimizer_states` (eager /
fused path) or :func:`paddle_tpu.distributed.auto.engine.make_train_step`
(the compiled TP+PP+ZeRO step).
"""
from __future__ import annotations

import warnings

_warned = set()


def _deprecated(name, instead):
    if name in _warned:
        return
    _warned.add(name)
    warnings.warn(
        f"paddle_tpu.distributed.sharding.{name} is deprecated; use "
        f"{instead} (see MIGRATING.md, 'fluid fleet -> mesh')",
        DeprecationWarning, stacklevel=3)


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2**23, segment_size=2**20,
                           sync_comm=False):
    """level: 'os' (stage1: optimizer states), 'os_g' (stage2: +grads),
    'p_g_os' (stage3: +params).  Requires an active mesh with a 'dp' axis
    (parallel.mesh.set_mesh / mesh_scope).  DEPRECATED alias of
    ``distributed.auto.zero.shard_optimizer_states``."""
    _deprecated("group_sharded_parallel",
                "distributed.auto.zero.shard_optimizer_states")
    from .auto import zero as auto_zero
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    auto_zero.shard_optimizer_states(optimizer, stage=stage, model=model)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    _deprecated("save_group_sharded_model",
                "io.serialization.save on state_dict()")
    from ..io.serialization import save
    save(model.state_dict(), output + ".pdmodel.params")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
