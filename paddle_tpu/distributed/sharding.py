"""Sharded (ZeRO) training (ref: python/paddle/distributed/sharding/ +
fleet sharding meta-optimizer).

TPU-native: optimizer-state sharding is a sharding-spec decision, not a
communication rewrite.  group_sharded_parallel marks params so that the
jitted train step places optimizer moments with a 'dp'-sharded
NamedSharding (stage 1/2); stage 3 also shards the params themselves and
XLA inserts the gather before use (fully-sharded data parallel).
"""
from __future__ import annotations

from ..parallel import mesh as mesh_mod


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2**23, segment_size=2**20,
                           sync_comm=False):
    """level: 'os' (stage1: optimizer states), 'os_g' (stage2: +grads),
    'p_g_os' (stage3: +params)."""
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    optimizer._zero_stage = stage
    if stage >= 3:
        for p in model.parameters():
            # shard params along their largest axis over dp
            shape = p.shape
            if not shape:
                continue
            axis = max(range(len(shape)), key=lambda i: shape[i])
            spec = [None] * len(shape)
            spec[axis] = "dp"
            p._sharding_axes = tuple(spec)
        mesh_mod.shard_params(model)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    from ..io.serialization import save
    save(model.state_dict(), output + ".pdmodel.params")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
