"""paddle.distributed.launch (ref: python/paddle/distributed/launch/).

Single-controller SPMD: on TPU pods each HOST runs one process of the same
script — XLA drives all local chips from one process, so the per-GPU
process fan-out of the reference maps to a per-host fan-out here.  The
launcher manages those processes for local testing (``--nproc-per-node``),
wires the coordinator env (``PADDLE_MASTER`` → jax.distributed.initialize
in init_parallel_env), waits on children, and tears the group down on the
first failure like the reference's elastic launcher.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def build_env(rank, nranks, master, base=None):
    env = dict(base if base is not None else os.environ)
    if master:
        env["PADDLE_MASTER"] = master
    env["PADDLE_TRAINERS_NUM"] = str(nranks)
    env["PADDLE_TRAINER_ID"] = str(rank)
    return env


def _free_local_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_procs(script_argv, nprocs, master, env_base=None, rank_base=0,
                 nranks=None):
    """Spawn nprocs copies of the script with per-rank env (global ranks
    rank_base..rank_base+nprocs-1 of nranks total); wait; kill the group
    on the first failure.  Returns the first nonzero exit code (0 if all
    succeeded).  With several local workers and no master given, a free
    local coordinator port is picked so the group really synchronizes
    (unsynced same-host replicas would silently train divergent models)."""
    nranks = nranks if nranks is not None else nprocs
    if master is None and nranks > 1:
        master = f"127.0.0.1:{_free_local_port()}"
    procs = []
    for i in range(nprocs):
        env = build_env(rank_base + i, nranks, master, env_base)
        procs.append(subprocess.Popen(
            [sys.executable] + script_argv, env=env))
    rc = 0
    try:
        remaining = set(range(nprocs))
        while remaining:
            for i in list(remaining):
                r = procs[i].poll()
                if r is None:
                    continue
                remaining.discard(i)
                if r != 0 and rc == 0:
                    rc = r
                    for j in remaining:
                        procs[j].send_signal(signal.SIGTERM)
            if remaining:
                time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return rc


def main(argv=None):
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--master", default=None,
                        help="coordinator address host:port")
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--rank", type=int, default=0,
                        help="this node's rank")
    parser.add_argument("--nproc-per-node", "--nproc_per_node", type=int,
                        default=1, dest="nproc_per_node",
                        help="local process fan-out (testing; on TPU one "
                             "process per host drives every chip)")
    parser.add_argument("--gpus", "--selected_gpus", default=None,
                        dest="gpus",
                        help="reference-era device list; on TPU it only "
                             "sets the per-node fan-out")
    parser.add_argument("--devices", default=None)
    parser.add_argument("--log_dir", "--log-dir", default=None,
                        dest="log_dir", help="accepted for reference "
                        "compatibility (workers inherit stdout/stderr)")
    parser.add_argument("--started_port", type=int, default=None,
                        help="accepted for reference compatibility")
    parser.add_argument("script", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if not args.script:
        parser.error("no training script given")
    if args.nnodes > 1 and not args.master:
        parser.error("--master host:port is required when --nnodes > 1")

    # Always RE-EXEC into fresh interpreters: this launcher process has
    # already imported paddle_tpu (and with it the XLA backend), so the
    # coordinator bootstrap can only fire in a clean child where the env
    # is set before `import paddle_tpu`.
    npp = max(args.nproc_per_node, 1)
    if npp == 1 and args.gpus:
        # reference behavior: one worker per listed device
        npp = len([g for g in args.gpus.split(",") if g.strip()])
    sys.exit(launch_procs(
        args.script, npp, args.master,
        rank_base=args.rank * npp,
        nranks=args.nnodes * npp))


if __name__ == "__main__":
    main()
