"""paddle.distributed.launch (ref: python/paddle/distributed/launch/).

Single-controller SPMD: on TPU pods each HOST runs one process of the same
script — XLA drives all local chips from one process, so the per-GPU
process fan-out of the reference maps to a per-host fan-out here.

The launcher is a SUPERVISOR (the reference's fleet elastic launcher /
TorchElastic worker-group model): it spawns the worker group, tees each
worker's stdout+stderr into ``--log_dir/workerN.log``, and on the first
nonzero exit records the incident, SIGTERMs the survivors exactly once,
and — within the ``--max-restarts`` budget, after exponential backoff —
re-rendezvouses the WHOLE group on a fresh coordinator port with
``PADDLE_RESTART_COUNT`` bumped so workers know their incarnation (and
resume from their last published checkpoint).  Budget exhausted, the
original failing exit code propagates and a machine-readable exit
summary (JSON) names the failing rank and its log file.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

# supervision counters, surfaced through profiler.fast_path_summary(); a
# VIEW over the observability registry's "launch" family (same storage)
from ..observability import metrics as _metrics

_launch_stats = _metrics.stats_family("launch", {
    "incidents": 0,          # worker failures observed
    "worker_restarts": 0,    # processes re-spawned after an incident
    "sigterms_sent": 0,      # group-teardown signals (once per survivor)
})


def launch_stats():
    return dict(_launch_stats)


def reset_launch_stats():
    for k in _launch_stats:
        _launch_stats[k] = 0


def build_env(rank, nranks, master, base=None):
    env = dict(base if base is not None else os.environ)
    if master:
        env["PADDLE_MASTER"] = master
    env["PADDLE_TRAINERS_NUM"] = str(nranks)
    env["PADDLE_TRAINER_ID"] = str(rank)
    return env


def _free_local_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# --------------------------------------------------------------------------
# reusable supervision hooks (shared by supervise() and non-training
# worker fleets, e.g. inference/fleet.py's serving replicas)
# --------------------------------------------------------------------------

def signal_name(rc):
    """Symbolic signal for a negative exit code (``-9`` -> ``"SIGKILL"``),
    or None for normal exits — the "was it killed, and by what" half of
    an incident record."""
    if rc is None or rc >= 0:
        return None
    try:
        return signal.Signals(-rc).name
    except ValueError:
        return f"signal {-rc}"


def backoff_delay(base, restarts_used, cap=60.0):
    """Exponential relaunch backoff: ``base * 2**restarts_used``, capped
    so a crash-looping worker fleet keeps retrying on a bounded cadence
    instead of sleeping into the hour range."""
    return min(base * (2 ** restarts_used), cap)


def spawn_worker(argv, env, log_path=None, python=True):
    """Spawn ONE supervised worker subprocess: stdout+stderr teed
    (unbuffered, append-mode — lines survive across incarnations) into
    ``log_path`` when given.  Returns a worker handle dict
    (``proc``/``log_f``/``log_path``) that :func:`stop_worker` and
    :func:`close_worker_log` consume.  ``python=True`` prefixes the
    current interpreter."""
    log_f = None
    if log_path:
        os.makedirs(os.path.dirname(os.path.abspath(log_path)),
                    exist_ok=True)
        log_path = os.path.abspath(log_path)
        # unbuffered fd + PYTHONUNBUFFERED: a killed worker's last lines
        # (usually the diagnosis) must reach the file
        log_f = open(log_path, "ab", buffering=0)
        env = dict(env)
        env.setdefault("PYTHONUNBUFFERED", "1")
    cmd = ([sys.executable] + list(argv)) if python else list(argv)
    try:
        proc = subprocess.Popen(
            cmd, env=env, stdout=log_f,
            stderr=subprocess.STDOUT if log_f else None)
    except Exception:
        if log_f is not None:
            log_f.close()
        raise
    return {"proc": proc, "log_f": log_f, "log_path": log_path}


def stop_worker(worker, term_grace=10.0):
    """SIGTERM one worker (exactly once — callers track their own
    already-signalled state for group semantics), SIGKILL whatever
    ignored it past the grace period.  Returns the exit code."""
    proc = worker["proc"]
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        _launch_stats["sigterms_sent"] += 1
    try:
        proc.wait(timeout=max(term_grace, 0.1))
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
    return proc.poll()


def close_worker_log(worker):
    if worker.get("log_f") is not None and not worker["log_f"].closed:
        worker["log_f"].close()


def incident_record(rank, rc, incarnation, log_path=None, t0=None,
                    also_failed=()):
    """One machine-readable incident: WHO failed (rank), HOW (exit code +
    decoded signal), WHEN (wall time, both absolute and relative to the
    supervisor's start), and the restart count at the moment of failure.
    The fleet router and ``bench.py --fleet`` consume these."""
    now = time.time()
    return {
        "time": now,
        "wall_time_s": round(now - t0, 3) if t0 is not None else None,
        "rank": rank,
        "exit_code": rc,
        "signal": signal_name(rc),
        "incarnation": incarnation,
        "restart_count": incarnation,
        "log": log_path,
        "also_failed": list(also_failed),
    }


def supervise(script_argv, nprocs, master=None, env_base=None, rank_base=0,
              nranks=None, log_dir=None, max_restarts=0, backoff=1.0,
              term_grace=10.0, poll_interval=0.2, telemetry_dir=None):
    """Run ``nprocs`` copies of the script under supervision (global ranks
    rank_base..rank_base+nprocs-1 of nranks total).  Returns a summary
    dict: ``rc`` (0, or the FIRST failing exit code of the final
    incident), ``restarts_used``, ``incidents`` (per-incident records:
    time + wall time since supervise() started, failing rank, exit code
    with the decoded signal when killed, restart count at failure, log
    path — what the fleet router and ``bench.py --fleet`` consume),
    ``failed_rank``/``failed_log`` for the terminal failure, and
    per-worker ``logs``.

    Restart semantics (TorchElastic worker-group model): any worker
    failing fails the GROUP — survivors get SIGTERM exactly once, then
    SIGKILL after ``term_grace`` — and within ``max_restarts`` the whole
    group relaunches after ``backoff * 2**restarts_used`` seconds on a
    FRESH coordinator port (when the port was auto-assigned; an explicit
    ``master`` is operator-owned and reused), with PADDLE_RESTART_COUNT
    telling workers their incarnation.  With several local workers and no
    master given, a free local coordinator port is picked so the group
    really synchronizes (unsynced same-host replicas would silently train
    divergent models).

    Scope: supervision is PER NODE — this process only watches the
    workers it spawned.  In a multi-node job each node's supervisor
    restarts independently (incarnation counters can diverge across
    nodes, and group re-formation relies on every node's relaunch landing
    within PADDLE_BOOTSTRAP_TIMEOUT); coordinated whole-job elasticity
    needs an external scheduler."""
    nranks = nranks if nranks is not None else nprocs
    auto_master = master is None and nranks > 1
    if telemetry_dir:
        os.makedirs(telemetry_dir, exist_ok=True)
    restarts_used = 0
    incidents = []
    log_paths = {}
    t0 = time.time()

    def spawn_group():
        m = f"127.0.0.1:{_free_local_port()}" if auto_master else master
        group = []
        try:
            _spawn_into(group, m)
        except Exception:
            # a mid-group failure (EMFILE, log_dir perms, ...) must not
            # leak the workers already started — they would rendezvous
            # forever on a coordinator that never fills, unsupervised
            stop_group(group)
            close_logs(group)
            raise
        return group

    def _spawn_into(group, m):
        for i in range(nprocs):
            rank = rank_base + i
            env = build_env(rank, nranks, m, env_base)
            env["PADDLE_RESTART_COUNT"] = str(restarts_used)
            if telemetry_dir:
                env["PADDLE_TELEMETRY_DIR"] = os.path.abspath(
                    telemetry_dir)
            log_path = None
            if log_dir:
                log_path = os.path.abspath(
                    os.path.join(log_dir, f"worker{rank}.log"))
                log_paths[rank] = log_path
            w = spawn_worker(script_argv, env, log_path=log_path)
            w["rank"] = rank
            group.append(w)

    def stop_group(group):
        """Tear down survivors: SIGTERM each still-running worker exactly
        once, then SIGKILL whatever ignored it past the grace period."""
        for w in group:
            if w["proc"].poll() is None:
                w["proc"].send_signal(signal.SIGTERM)
                _launch_stats["sigterms_sent"] += 1
        deadline = time.time() + term_grace
        for w in group:
            try:
                w["proc"].wait(timeout=max(deadline - time.time(), 0.1))
            except subprocess.TimeoutExpired:
                w["proc"].kill()
                w["proc"].wait()

    def close_logs(group):
        for w in group:
            close_worker_log(w)

    workers = spawn_group()
    rc = 0
    try:
        while True:
            failed = None
            running = 0
            also_failed = []
            for w in workers:
                r = w["proc"].poll()
                if r is None:
                    running += 1
                elif r != 0:
                    if failed is None:
                        failed = (w, r)
                    else:
                        # poll() can't order deaths inside one sweep —
                        # record every failure so the root cause is
                        # never silently dropped from the summary
                        also_failed.append(
                            {"rank": w["rank"], "exit_code": r})
            if failed is not None:
                w, r = failed
                _launch_stats["incidents"] += 1
                incidents.append(incident_record(
                    w["rank"], r, restarts_used, log_path=w["log_path"],
                    t0=t0, also_failed=also_failed))
                stop_group(workers)
                close_logs(workers)
                if restarts_used < max_restarts:
                    delay = backoff * (2 ** restarts_used)
                    restarts_used += 1
                    _launch_stats["worker_restarts"] += nprocs
                    time.sleep(delay)
                    workers = spawn_group()   # fresh port when auto_master
                    continue
                rc = r
                break
            if running == 0:
                break
            time.sleep(poll_interval)
    finally:
        for w in workers:
            if w["proc"].poll() is None:
                w["proc"].kill()
        close_logs(workers)
    last = incidents[-1] if rc != 0 and incidents else None
    return {
        "rc": rc,
        "nprocs": nprocs,
        "nranks": nranks,
        "max_restarts": max_restarts,
        "restarts_used": restarts_used,
        "incidents": incidents,
        "failed_rank": last["rank"] if last else None,
        "failed_log": last["log"] if last else None,
        "logs": dict(log_paths),
        # where the workers' JSONL event logs landed, so the exit summary
        # points straight into the step-by-step record of the failure
        "telemetry_dir": (os.path.abspath(telemetry_dir)
                          if telemetry_dir else None),
        "duration_s": round(time.time() - t0, 3),
    }


def launch_procs(script_argv, nprocs, master, env_base=None, rank_base=0,
                 nranks=None, **supervise_kwargs):
    """Back-compat wrapper over :func:`supervise`: spawn, wait, return the
    first nonzero exit code (0 if all succeeded; kills the group on the
    first failure when no restart budget is given)."""
    return supervise(script_argv, nprocs, master, env_base=env_base,
                     rank_base=rank_base, nranks=nranks,
                     **supervise_kwargs)["rc"]


def main(argv=None):
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--master", default=None,
                        help="coordinator address host:port")
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--rank", type=int, default=0,
                        help="this node's rank")
    parser.add_argument("--nproc-per-node", "--nproc_per_node", type=int,
                        default=1, dest="nproc_per_node",
                        help="local process fan-out (testing; on TPU one "
                             "process per host drives every chip)")
    parser.add_argument("--gpus", "--selected_gpus", default=None,
                        dest="gpus",
                        help="reference-era device list; on TPU it only "
                             "sets the per-node fan-out")
    parser.add_argument("--devices", default=None)
    parser.add_argument("--log_dir", "--log-dir", default=None,
                        dest="log_dir",
                        help="per-worker log directory: each worker's "
                             "stdout+stderr tees into workerN.log")
    parser.add_argument("--max-restarts", "--max_restarts", type=int,
                        default=0, dest="max_restarts",
                        help="elastic restart budget: on a worker failure "
                             "the whole group is torn down and relaunched "
                             "(fresh coordinator port, exponential "
                             "backoff) up to this many times")
    parser.add_argument("--restart-backoff", type=float, default=1.0,
                        dest="restart_backoff",
                        help="base seconds of the exponential relaunch "
                             "backoff (doubles per incident)")
    parser.add_argument("--started_port", type=int, default=None,
                        help="accepted for reference compatibility")
    parser.add_argument("--telemetry", nargs="?", const="auto",
                        default=None, metavar="DIR",
                        help="enable worker telemetry: sets "
                             "PADDLE_TELEMETRY_DIR for every worker "
                             "(DIR, or <log_dir>/telemetry, or "
                             "./telemetry) and prints the merged "
                             "cross-rank report on exit")
    parser.add_argument("script", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if not args.script:
        parser.error("no training script given")
    if args.nnodes > 1 and not args.master:
        parser.error("--master host:port is required when --nnodes > 1")
    if args.nnodes > 1 and args.max_restarts > 0:
        print("paddle_tpu.launch: warning — --max-restarts supervises "
              "THIS node only; other nodes restart on their own "
              "schedule and incarnation counters may diverge (see "
              "supervise() docstring)", file=sys.stderr)

    # Always RE-EXEC into fresh interpreters: this launcher process has
    # already imported paddle_tpu (and with it the XLA backend), so the
    # coordinator bootstrap can only fire in a clean child where the env
    # is set before `import paddle_tpu`.
    npp = max(args.nproc_per_node, 1)
    if npp == 1 and args.gpus:
        # reference behavior: one worker per listed device
        npp = len([g for g in args.gpus.split(",") if g.strip()])
    telemetry_dir = args.telemetry
    if telemetry_dir == "auto":
        telemetry_dir = os.path.join(args.log_dir or ".", "telemetry")
    summary = supervise(
        args.script, npp, args.master,
        rank_base=args.rank * npp,
        nranks=args.nnodes * npp,
        log_dir=args.log_dir,
        max_restarts=args.max_restarts,
        backoff=args.restart_backoff,
        telemetry_dir=telemetry_dir)
    if telemetry_dir:
        # merged cross-rank view: per-rank step times, stragglers, fault
        # counters — rendered from the telemetry dir the workers wrote
        try:
            from ..observability import aggregate
            report = aggregate.merge_from_dir(telemetry_dir)
            summary["telemetry_report"] = report
            print(aggregate.format_report(report), file=sys.stderr)
        except Exception as e:                             # noqa: BLE001
            print(f"paddle_tpu.launch: telemetry report failed: {e}",
                  file=sys.stderr)
    # machine-readable exit summary: one JSON line, greppable by drivers
    print(json.dumps({"event": "paddle_tpu.launch.exit", **summary}),
          flush=True)
    if summary["rc"] != 0 and summary["failed_log"]:
        print(f"paddle_tpu.launch: rank {summary['failed_rank']} failed "
              f"with exit code {summary['rc']} — see its log: "
              f"{summary['failed_log']}", file=sys.stderr)
    sys.exit(summary["rc"])


if __name__ == "__main__":
    main()
