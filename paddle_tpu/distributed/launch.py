"""paddle.distributed.launch (ref: python/paddle/distributed/launch.py).

Single-controller SPMD: on TPU pods each HOST runs one process of the same
script; this launcher sets the coordinator env and execs the training script
once per host (the per-device process fan-out of the reference does not
apply — XLA drives all local chips from one process).
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys


def main():
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--master", default=None,
                        help="coordinator address host:port")
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--rank", type=int, default=0)
    parser.add_argument("--gpus", default=None, help="ignored on TPU")
    parser.add_argument("--devices", default=None)
    parser.add_argument("script", nargs=argparse.REMAINDER)
    args = parser.parse_args()

    if args.master:
        os.environ["PADDLE_MASTER"] = args.master
    os.environ["PADDLE_TRAINERS_NUM"] = str(args.nnodes)
    os.environ["PADDLE_TRAINER_ID"] = str(args.rank)

    if not args.script:
        parser.error("no training script given")
    script = args.script[0]
    sys.argv = args.script
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    main()
