"""Profiler/tracing (ref: python/paddle/fluid/profiler.py +
paddle/fluid/platform/profiler.cc).

TPU-native: wraps jax.profiler for device traces (viewable in TensorBoard /
xprof) plus a lightweight host-side op timer for eager mode.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

_op_times = defaultdict(float)
_op_counts = defaultdict(int)
_enabled = False


def start_profiler(state="All", tracer_option="Default", log_dir=None):
    global _enabled
    _enabled = True
    if log_dir:
        jax.profiler.start_trace(log_dir)
    _op_times.clear()
    _op_counts.clear()


def stop_profiler(sorted_key="total", profile_path=None):
    global _enabled
    _enabled = False
    try:
        jax.profiler.stop_trace()
    except RuntimeError:
        pass
    return summary()


def summary():
    rows = sorted(_op_times.items(), key=lambda kv: -kv[1])
    out = [("op", "count", "total_s")]
    for name, t in rows:
        out.append((name, _op_counts[name], round(t, 6)))
    return out


def record_op(name, seconds):
    if _enabled:
        _op_times[name] += seconds
        _op_counts[name] += 1


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None):
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def record_event(name):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_op(name, time.perf_counter() - t0)


class RecordEvent:
    def __init__(self, name):
        self.name = name
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *a):
        record_op(self.name, time.perf_counter() - self._t0)


def trace(log_dir):
    """Device-level trace context via jax.profiler (xprof format)."""
    return jax.profiler.trace(log_dir)
