"""Profiler/tracing (ref: python/paddle/fluid/profiler.py,
python/paddle/utils/profiler.py + paddle/fluid/platform/profiler.cc).

TPU-native: device-side traces ride ``jax.profiler`` (xprof, viewable in
TensorBoard), host-side eager dispatch is timed per op through the
``ops.dispatch`` hook, and the collected events export to the
chrome://tracing JSON format like the reference's profiler.cc exporter.
Eager timings measure host dispatch latency (XLA execution is async);
device truth comes from the xprof trace.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
import warnings
from collections import defaultdict

import jax

from .observability import metrics
from .observability import timeline as _timeline

_op_times = defaultdict(float)
_op_counts = defaultdict(int)
_events = []                    # (name, t_start, dur) host-side
_events_lock = threading.Lock()
_enabled = False
_t0 = 0.0


def reset_profiler():
    """Drop collected op records (ref fluid/profiler.py::reset_profiler).
    Takes the events lock — record_op appends under it from worker
    threads."""
    _op_times.clear()
    _op_counts.clear()
    with _events_lock:
        del _events[:]


def start_profiler(state="All", tracer_option="Default", log_dir=None):
    global _enabled, _t0
    _enabled = True
    _t0 = time.perf_counter()
    # compile events must reach the trace: retraces during the profiled
    # window appear as xla_compile events (observability/timeline.py)
    _timeline.install_compile_hook()
    if log_dir:
        jax.profiler.start_trace(log_dir)
    reset_profiler()


def stop_profiler(sorted_key="total", profile_path=None):
    global _enabled
    _enabled = False
    try:
        jax.profiler.stop_trace()
    except RuntimeError:
        pass
    if profile_path:
        export_chrome_tracing(profile_path)
    return summary()


def is_enabled():
    return _enabled


def summary():
    rows = sorted(_op_times.items(), key=lambda kv: -kv[1])
    out = [("op", "count", "total_s")]
    for name, t in rows:
        out.append((name, _op_counts[name], round(t, 6)))
    return out


def record_op(name, seconds, t_start=None):
    if _enabled:
        # the totals must be updated under the lock too: DataLoader
        # worker threads dispatch ops concurrently and an unlocked
        # read-add-write drops increments
        with _events_lock:
            _op_times[name] += seconds
            _op_counts[name] += 1
            _events.append((name, (t_start if t_start is not None
                                   else time.perf_counter() - seconds)
                            - _t0, seconds))


def export_chrome_tracing(path):
    """Write collected host events as chrome://tracing 'X' events
    (the reference's profiler.cc emits the same format)."""
    trace = {"traceEvents": [
        {"name": name, "ph": "X", "pid": 0, "tid": 0,
         "ts": round(ts * 1e6, 3), "dur": round(dur * 1e6, 3),
         "cat": "op"}
        for name, ts, dur in _events]}
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None):
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def record_event(name):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_op(name, time.perf_counter() - t0, t_start=t0)


class RecordEvent:
    def __init__(self, name):
        self.name = name
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *a):
        record_op(self.name, time.perf_counter() - self._t0,
                  t_start=self._t0)


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"       # accepted for parity; maps to the accelerator
    TPU = "tpu"


class Profiler:
    """paddle.profiler.Profiler-style session (ref:
    python/paddle/profiler/profiler.py in later reference versions;
    start/stop/step lifecycle with an optional chrome-trace export)."""

    def __init__(self, targets=(ProfilerTarget.CPU, ProfilerTarget.TPU),
                 scheduler=None, on_trace_ready=None, log_dir=None):
        self.targets = tuple(targets)
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.log_dir = log_dir
        self._step = 0
        self._step_t0 = None

    def start(self):
        start_profiler(log_dir=self.log_dir)
        self._step_t0 = time.perf_counter()
        return self

    def stop(self):
        result = stop_profiler()
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)
        return result

    def step(self):
        """Close the span covering everything since the previous step()
        (or start()) and open the next one — exported chrome traces show
        real step boundaries, not the zero-duration markers this used to
        record."""
        self._step += 1
        now = time.perf_counter()
        t0 = self._step_t0 if self._step_t0 is not None else now
        record_op("profiler_step", now - t0, t_start=t0)
        self._step_t0 = now

    def step_num(self):
        return self._step

    def summary(self, sorted_by="total", **kwargs):
        return summary()

    def export_chrome_tracing(self, path):
        return export_chrome_tracing(path)

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()


def trace(log_dir):
    """Device-level trace context via jax.profiler (xprof format)."""
    return jax.profiler.trace(log_dir)


# --------------------------------------------------------------------------
# Eager fast-path counters (dispatch jit-cache + fused optimizer step)
#
# Every family below is a VIEW over the observability metrics registry
# (paddle_tpu.observability.metrics): the module-level stat dicts ARE
# registry-backed, so these functions, metrics.snapshot() and the
# Prometheus/JSONL exports all read the same cells — no dual bookkeeping.
# --------------------------------------------------------------------------

_deprecated_reset_warned = set()


def _warn_reset_deprecated(name, family):
    if name in _deprecated_reset_warned:
        return
    _deprecated_reset_warned.add(name)
    warnings.warn(
        f"profiler.{name}() is deprecated: the per-family reset helpers "
        f"are served by the observability metrics registry — use "
        f"paddle_tpu.observability.metrics.reset({family!r}) (or "
        f"metrics.reset() for everything)", DeprecationWarning,
        stacklevel=3)


def dispatch_cache_stats():
    """Hit/miss/retrace counters of the eager dispatch executable cache
    (ops.dispatch).  A miss IS a retrace — it traces and compiles a new
    executable; steady-state training loops should show misses flat."""
    from .ops import dispatch
    return dispatch.cache_stats()


def reset_dispatch_cache_stats():
    _warn_reset_deprecated("reset_dispatch_cache_stats", "dispatch_cache")
    from .ops import dispatch
    dispatch.reset_cache_stats()


def fused_step_stats():
    """Counters of the fused optimizer step: ``calls`` is one per
    optimizer.step() on the fused path (one XLA dispatch each),
    ``compiles`` counts signature retraces."""
    from .optimizer import optimizer as _opt
    return dict(_opt._fused_stats)


def reset_fused_step_stats():
    _warn_reset_deprecated("reset_fused_step_stats", "fused_step")
    metrics.reset("fused_step")


def reducer_stats():
    """Counters of the overlap-scheduled bucketed gradient reducer
    (distributed/reducer.py): buckets built, collectives launched (one
    per bucket per step), how many launched from grad-ready hooks
    mid-backward vs at finalize (overlap_ratio), zero-filled grad-less
    params."""
    from .distributed import reducer as _red
    return _red.reducer_stats()


def reset_reducer_stats():
    _warn_reset_deprecated("reset_reducer_stats", "reducer")
    metrics.reset("reducer")


def prefetch_stats():
    """Device-side input prefetch counters (io/dataloader.py
    prefetch_to_device): a hit is a batch whose H2D transfer finished
    before the training loop asked for it."""
    from .io import dataloader as _dl
    return _dl.prefetch_stats()


def reset_prefetch_stats():
    _warn_reset_deprecated("reset_prefetch_stats", "prefetch")
    metrics.reset("prefetch")


def faults_stats():
    """Fault-tolerance counter family: collective watchdog expiries and
    absorbed KV-store retries, launcher supervision incidents/restarts,
    checkpoint integrity events (async publishes, digest failures,
    quarantines, restore fallbacks), bootstrap connection retries, and
    injected-fault fires from the chaos harness
    (paddle_tpu.testing.faults)."""
    import importlib
    out = {}
    # one import per family so a single broken module can't hide every
    # counter; "paddle_tpu.distributed.launch" is spelled out because
    # the distributed package exports a launch() FUNCTION shadowing the
    # submodule attribute
    for mod, fn in (("paddle_tpu.distributed.collective", "watchdog_stats"),
                    ("paddle_tpu.distributed.launch", "launch_stats"),
                    ("paddle_tpu.utils.checkpoint", "checkpoint_stats"),
                    ("paddle_tpu._dist_bootstrap", "bootstrap_stats"),
                    ("paddle_tpu.testing.faults", "fault_stats")):
        try:
            out.update(getattr(importlib.import_module(mod), fn)())
        except Exception:                                  # noqa: BLE001
            pass
    return out


def serving_stats():
    """Serving-engine counter family (inference/serving.py): bucketed
    prefill / decode-step compiles and calls, request admissions and
    completions, generated tokens, queue rejects, and the standalone
    predictor's per-signature compiles.  Read straight from the registry
    — importing the serving stack (GPT core + Pallas) just to read
    counters would defeat its lazy loading; a process that never served
    simply reports an empty family."""
    return metrics.families().get("serving", {})


def reset_serving_stats():
    _warn_reset_deprecated("reset_serving_stats", "serving")
    metrics.reset("serving")


def fleet_stats():
    """Serving-fleet router counter family (inference/fleet.py):
    admissions/completions/failures, re-queues and retries, load sheds
    (per priority class), heartbeat misses, replica incidents/restarts,
    scale ups/downs, dedupe hits.  A pure registry read (a process that
    never routed reports an empty family)."""
    return metrics.families().get("fleet", {})


def autoscale_stats():
    """Fleet-autoscaler counter family (inference/autoscale.py): control
    ticks, scale ups/downs, cooldown/bound holds, per-signal up
    triggers, isolated tick errors.  A pure registry read (a process
    that never autoscaled reports an empty family)."""
    return metrics.families().get("autoscale", {})


def sharding_stats():
    """Model-parallel subsystem counter family (distributed/auto):
    per-axis collective counts/bytes from the composed step's static
    plan, ZeRO sharded/replicated leaf counts, pipeline bubble fraction,
    per-device param/optimizer-state bytes.  A pure registry read (a
    process that never built a parallel step reports an empty family);
    the derived ``opt_state_shrink`` ratio rides along when the family
    is live."""
    fam = metrics.families().get("sharding", {})
    if fam and fam.get("opt_state_bytes_per_device"):
        fam = dict(fam)
        fam["opt_state_shrink"] = round(
            fam["opt_state_bytes_replicated"]
            / fam["opt_state_bytes_per_device"], 4)
    return fam


def analysis_stats():
    """Static-analyzer counter family (paddle_tpu/analysis): findings by
    rule id, new vs baselined, suppressions, baseline size/staleness,
    files scanned.  A pure registry read — populated when an analyzer
    run (``python -m paddle_tpu.analysis`` or the tools/ guards)
    executed in this process; empty otherwise, so lint posture rides
    beside the runtime counters wherever both exist."""
    return metrics.families().get("analysis", {})


def compile_stats():
    """The ONE compile-management family (framework/compile_cache.py,
    ISSUE 14): unified cache hits/builds/evictions (plus per-site
    ``<site>_builds`` breakdowns), the AOT artifact-store counters
    (``aot_hits``/``aot_misses``/``aot_saves``/``aot_errors``/
    ``aot_stale``), the absorbed persistent-compilation-cache counters,
    and the timeline compile hook's backend-compile ``count``/
    ``seconds``.  The seven retired per-site cache counter families
    (``dispatch_cache.*``, ``fused_step.compiles``,
    ``serving.*_compiles`` …) remain as ALIASED views fed by this
    layer."""
    return metrics.families().get("compile", {})


def fast_path_summary():
    """One dict with every fast-path counter family — what the bench.py
    eager microbench and dp-overlap bench assert on — plus the ``faults``
    family the recovery bench and chaos tests assert on and the
    ``serving`` family the serving bench asserts on."""
    out = {"dispatch_cache": dispatch_cache_stats()}
    for key, fn in (("fused_step", fused_step_stats),
                    ("reducer", reducer_stats),
                    ("prefetch", prefetch_stats),
                    ("faults", faults_stats),
                    ("serving", serving_stats),
                    ("fleet", fleet_stats),
                    ("autoscale", autoscale_stats),
                    ("sharding", sharding_stats),
                    ("analysis", analysis_stats),
                    ("compile", compile_stats)):
        try:
            out[key] = fn()
        except Exception:                                  # noqa: BLE001
            out[key] = {}
    return out
