"""Metrics (ref: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..tensor.tensor import Tensor


class Metric:
    def __init__(self):
        self._name = self.__class__.__name__.lower()

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred_np = pred.numpy() if isinstance(pred, Tensor) else np.asarray(pred)
        label_np = (label.numpy() if isinstance(label, Tensor)
                    else np.asarray(label))
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = np.squeeze(label_np, -1)
        correct = topk_idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = correct.numpy() if isinstance(correct, Tensor) else np.asarray(correct)
        num = c.shape[0] if c.ndim > 0 else 1
        accs = []
        for i, k in enumerate(self.topk):
            n_correct = c[..., :k].sum()
            self.total[i] += n_correct
            self.count[i] += num
            accs.append(float(n_correct) / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [float(t / max(c, 1)) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (preds.numpy() if isinstance(preds, Tensor)
             else np.asarray(preds)).reshape(-1)
        l = (labels.numpy() if isinstance(labels, Tensor)
             else np.asarray(labels)).reshape(-1)
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fp += int(np.sum(pred_pos & (l != 1)))

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (preds.numpy() if isinstance(preds, Tensor)
             else np.asarray(preds)).reshape(-1)
        l = (labels.numpy() if isinstance(labels, Tensor)
             else np.asarray(labels)).reshape(-1)
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fn += int(np.sum(~pred_pos & (l == 1)))

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = (labels.numpy() if isinstance(labels, Tensor)
             else np.asarray(labels)).reshape(-1)
        if p.ndim == 2:
            p = p[:, -1]
        idx = np.clip((p * self.num_thresholds).astype(int), 0,
                      self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds descending, anchored at (0,0) like
        # the reference's loop starting from tot_pos=tot_neg=0 — without
        # the anchor the first trapezoid's area is dropped (degenerate
        # one-bucket distributions returned 0.0 instead of 0.5)
        tp = np.concatenate([[0.0], np.cumsum(self._stat_pos[::-1])])
        fp = np.concatenate([[0.0], np.cumsum(self._stat_neg[::-1])])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


def accuracy(input, label, k=1, correct=None, total=None):
    p = input.numpy()
    l = label.numpy()
    topk_idx = np.argsort(-p, axis=-1)[..., :k]
    if l.ndim == p.ndim:
        l = np.squeeze(l, -1)
    correct_ = (topk_idx == l[..., None]).any(-1)
    return Tensor(np.asarray(correct_.mean(), np.float32))
