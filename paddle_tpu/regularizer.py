"""Regularizers (ref: python/paddle/regularizer.py)."""
from __future__ import annotations

import jax.numpy as jnp


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = coeff

    def grad_term(self, p):
        return self._coeff * jnp.sign(p)


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = coeff

    def grad_term(self, p):
        return self._coeff * p


L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay

