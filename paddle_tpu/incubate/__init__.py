"""paddle.incubate (ref: python/paddle/incubate/): staging ground.

Hosts the TPU-extras that go beyond the stable Paddle 2.0 surface: ring
attention for long context and fused Pallas ops.
"""
from ..parallel.ring_attention import ring_attention_sharded as ring_attention
from ..parallel import moe
from ..nn.functional.attention import flash_attention
from ..nn.functional.norm import rms_norm


def fused_feedforward(x, w1, b1, w2, b2):
    """gelu(x@w1+b1)@w2+b2 in one Pallas kernel (eager, differentiable;
    the reference grows the same op as fused_feedforward in
    paddle/fluid/operators/fused/fused_feedforward_op.cu)."""
    from ..ops import dispatch
    from ..ops.pallas.fused_ffn import fused_ffn as _ffn
    return dispatch.call(lambda a, *p: _ffn(a, *p), x, w1, b1, w2, b2,
                         _name="fused_feedforward")


def fused_layer_norm(x, weight, bias, epsilon=1e-5):
    """Fused last-axis LayerNorm Pallas kernel (eager, differentiable)."""
    from ..ops import dispatch
    from ..ops.pallas.norms import layer_norm as _ln
    return dispatch.call(lambda a, w, b: _ln(a, w, b, epsilon),
                         x, weight, bias, _name="fused_layer_norm")

from . import optimizer
from .optimizer import LookAhead, ModelAverage
