"""paddle.incubate (ref: python/paddle/incubate/): staging ground.

Hosts the TPU-extras that go beyond the stable Paddle 2.0 surface: ring
attention for long context and fused Pallas ops.
"""
from ..parallel.ring_attention import ring_attention_sharded as ring_attention
from ..nn.functional.attention import flash_attention
from ..nn.functional.norm import rms_norm
