"""paddle.incubate.optimizer — LookAhead and ModelAverage (ref:
python/paddle/incubate/optimizer/{lookahead,modelaverage}.py).

Both are weight-space wrappers, not gradient transforms, so they sit
OUTSIDE the jitted inner step: the inner optimizer's fused update runs
compiled; the slow-weight interpolation / running average is a cheap
device-side tree op every k steps."""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from ..optimizer.optimizer import Optimizer


class LookAhead(Optimizer):
    """k fast steps, then slow <- slow + alpha * (fast - slow); fast <-
    slow (Zhang et al. 2019; ref incubate/optimizer/lookahead.py)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        super().__init__(learning_rate=inner_optimizer._lr,
                         parameters=list(inner_optimizer._parameters))
        self._slow = {id(p): p.value for p in self._parameters}

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            for p in self._parameters:
                slow = self._slow[id(p)]
                slow = slow + self.alpha * (p.value - slow)
                self._slow[id(p)] = slow
                p.value = slow

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_slow"] = {i: v for i, (k, v) in
                                enumerate(self._slow.items())}
        sd["lookahead_step"] = self._step_count
        return sd


class ModelAverage(Optimizer):
    """Running average of parameters over a sliding window; ``apply()``
    swaps the averaged weights in for evaluation, ``restore()`` swaps the
    training weights back (ref incubate/optimizer/modelaverage.py — there
    via sum_1/sum_2/sum_3 accumulator rotation; one running (sum, count)
    with the same window clamping behaves identically for the window
    sizes involved)."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters)
        self.rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)
        self._sum = {id(p): jnp.zeros_like(p.value)
                     for p in self._parameters}
        self._count = 0
        self._backup = None

    def step(self):
        self._step_count += 1
        window = max(self.min_window,
                     min(self.max_window,
                         int(self._step_count * self.rate)))
        if self._count >= window:
            # slide: decay the sum so old steps wash out (the reference
            # rotates its sum_1/2/3 blocks for the same effect)
            keep = (window - 1) / window
            for k in self._sum:
                self._sum[k] = self._sum[k] * keep
            self._count = int(self._count * keep)
        for p in self._parameters:
            self._sum[id(p)] = self._sum[id(p)] + p.value
        self._count += 1

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
        return None, None

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Swap in averaged weights (context manager, reference API)."""
        self._backup = {id(p): p.value for p in self._parameters}
        denom = max(self._count, 1)
        for p in self._parameters:
            p.value = self._sum[id(p)] / denom
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is not None:
            for p in self._parameters:
                p.value = self._backup[id(p)]
            self._backup = None


class ExponentialMovingAverage:
    """ref fluid/optimizer.py::ExponentialMovingAverage — EMA of params
    with optional Adam-style bias correction (thres_steps unsupported);
    ``update()`` after each step, ``apply()``/``restore()`` around eval."""

    def __init__(self, decay=0.999, thres_steps=None, name=None,
                 parameters=None):
        from ..static.graph import default_main_program, in_static_mode
        if parameters is None and in_static_mode():
            parameters = default_main_program().all_parameters()
        self._decay = float(decay)
        self._parameters = list(parameters or [])
        # zero-init + bias correction (the Adam-style estimator the
        # reference uses): ema_t / (1 - decay^t) is unbiased from step 1
        self._ema = {id(p): jnp.zeros_like(p.value)
                     for p in self._parameters}
        self._step = 0
        self._backup = None

    def update(self):
        self._step += 1
        d = self._decay
        for p in self._parameters:
            self._ema[id(p)] = d * self._ema[id(p)] + (1 - d) * p.value

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p.value for p in self._parameters}
        corr = 1.0 - self._decay ** max(self._step, 1)
        for p in self._parameters:
            p.value = self._ema[id(p)] / corr
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is not None:
            for p in self._parameters:
                p.value = self._backup[id(p)]
            self._backup = None
