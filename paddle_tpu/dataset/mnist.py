"""paddle.dataset.mnist (ref: python/paddle/dataset/mnist.py).

train()/test() yield (image float32[784] scaled to [-1, 1], int label) —
the reference's exact sample schema."""
from __future__ import annotations

import numpy as np


def _reader_creator(mode):
    def reader():
        from ..vision.datasets import MNIST
        ds = MNIST(mode=mode)
        for img, label in ((ds.images[i], ds.labels[i])
                           for i in range(len(ds))):
            flat = img.astype(np.float32).reshape(-1) / 127.5 - 1.0
            yield flat, int(label)
    return reader


def train(image_path=None, label_path=None):
    if image_path is not None:
        def reader():
            from ..vision.datasets.mnist import (parse_idx_images,
                                                 parse_idx_labels)
            images = parse_idx_images(image_path)
            labels = parse_idx_labels(label_path)
            for i in range(len(images)):
                yield (images[i].astype(np.float32).reshape(-1) / 127.5
                       - 1.0, int(labels[i]))
        return reader
    return _reader_creator("train")


def test(image_path=None, label_path=None):
    if image_path is not None:
        return train(image_path, label_path)
    return _reader_creator("test")
