"""paddle.dataset.imdb (ref: python/paddle/dataset/imdb.py).

word_dict() -> {word: id}; train(word_idx)/test(word_idx) yield
([token ids], 0/1 label)."""
from __future__ import annotations

import numpy as np


def word_dict(data_file=None, cutoff=150):
    from ..text.datasets import Imdb
    return Imdb(data_file=data_file, mode="train", cutoff=cutoff).word_idx


def _reader_creator(mode, word_idx, data_file=None):
    def reader():
        from ..text.datasets import Imdb
        ds = Imdb(data_file=data_file, mode=mode)
        for doc, label in (ds[i] for i in range(len(ds))):
            yield [int(t) for t in doc], int(label)
    return reader


def train(word_idx=None, data_file=None):
    return _reader_creator("train", word_idx, data_file)


def test(word_idx=None, data_file=None):
    return _reader_creator("test", word_idx, data_file)
