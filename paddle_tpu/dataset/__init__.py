"""paddle.dataset — legacy reader-creator data modules.

Re-design of the reference's module-level dataset readers
(ref: python/paddle/dataset/{mnist,cifar,uci_housing,imdb}.py): each
sub-module exposes ``train()``/``test()`` returning zero-arg reader
creators that yield one sample at a time — the shape the
``paddle.reader`` decorators and ``paddle.batch`` compose over.  Backed
by the modern dataset classes (real-file parsing when paths are given,
deterministic synthetic data otherwise in this zero-egress environment).
"""
from . import mnist
from . import cifar
from . import uci_housing
from . import imdb

__all__ = ["mnist", "cifar", "uci_housing", "imdb"]
