"""paddle.dataset.cifar (ref: python/paddle/dataset/cifar.py).

train10/test10/train100/test100 yield (float32[3072] scaled to [0,1],
int label)."""
from __future__ import annotations

import numpy as np


def _reader_creator(cls_name, mode, data_file=None):
    def reader():
        from ..vision import datasets as vd
        ds = getattr(vd, cls_name)(data_file=data_file, mode=mode)
        for i in range(len(ds)):
            img = ds.images[i].astype(np.float32) / 255.0
            # reference layout: flat [C*H*W]
            yield img.transpose(2, 0, 1).reshape(-1), int(ds.labels[i])
    return reader


def train10(data_file=None):
    return _reader_creator("Cifar10", "train", data_file)


def test10(data_file=None):
    return _reader_creator("Cifar10", "test", data_file)


def train100(data_file=None):
    return _reader_creator("Cifar100", "train", data_file)


def test100(data_file=None):
    return _reader_creator("Cifar100", "test", data_file)
