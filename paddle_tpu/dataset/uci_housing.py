"""paddle.dataset.uci_housing (ref: python/paddle/dataset/uci_housing.py).

train()/test() yield (features float32[13], price float32[1])."""
from __future__ import annotations

import numpy as np


def _reader_creator(mode, data_file=None):
    def reader():
        from ..text.datasets import UCIHousing
        ds = UCIHousing(data_file=data_file, mode=mode)
        for x, y in (ds[i] for i in range(len(ds))):
            yield np.asarray(x, np.float32), np.asarray(y, np.float32)
    return reader


def train(data_file=None):
    return _reader_creator("train", data_file)


def test(data_file=None):
    return _reader_creator("test", data_file)
