"""High-throughput serving engine: continuous batching over a slot-pooled
KV cache (ISSUE 5 tentpole).

The reference serves frozen programs through a request-at-a-time predictor
(ref: paddle/fluid/inference/api/analysis_predictor.cc) — fine for CNNs,
hopeless for autoregressive decoding, where request-level batching wastes
most of the batch on padding and parks finished sequences until the
slowest one drains.  This engine is the Orca/vLLM-shaped redesign:

* **slots, not batches** — a fixed pool of ``slots`` decode lanes backed
  by ONE shared ``[L, slots, max_len, nh, hd]`` KV buffer with a per-slot
  fill length (models/gpt.py::init_slot_cache).  Every iteration one
  jitted, **buffer-donated** decode step (models/gpt.py::decode_step_slots)
  advances all in-flight sequences a token; a finished sequence's slot is
  handed to the next queued request immediately — no drain barrier, no
  padding rows beyond the pool size.  The decode executable's signature
  never changes, so requests churning through slots cost ZERO retraces.
* **bucketed prefill** — prompts are padded to a ``(batch, seq)`` shape
  ladder and prefilled through per-bucket executables (cached in a
  :class:`~paddle_tpu.ops.dispatch.SignatureLRU`, the dispatch cache's
  keying discipline), so compile count is bounded by the ladder size no
  matter how many distinct prompt lengths arrive.  Each prefill executable
  also scatters its K/V rows straight into the donated slot buffer and
  returns the first sampled token — one XLA program per admission wave.
* **persistent compiles** — ``PADDLE_JIT_CACHE_DIR`` (via
  framework/jax_compat.py::enable_persistent_cache) makes a server restart
  reload yesterday's executables instead of re-running XLA.

Telemetry rides the PR-4 registry under ``serving.*``: queue depth and
slot occupancy gauges, prefill/decode/request latency histograms,
tokens/s, and compile counters the bench asserts on.
"""
from __future__ import annotations

import collections
import os
import time
import uuid

import numpy as np

from ..framework import jax_compat
from ..models import gpt
from ..observability import metrics, timeline
from ..ops.dispatch import SignatureLRU
from ..testing import faults as _faults

DEFAULT_BATCH_BUCKETS = (1, 2, 4)


class ServingQueueFull(RuntimeError):
    """submit() back-pressure: the bounded admission queue is at
    ``max_queue`` — callers must retry/shed, exactly like a 429."""


def _donation_enabled():
    """Donate the slot KV buffers into prefill/decode executables
    (in-place update, no second cache-sized allocation).  Same contract
    as the fused optimizer step: ``PADDLE_TPU_SERVING_DONATE`` 0/1
    forces, auto skips CPU (whose donation path only warns)."""
    return jax_compat.donation_enabled("PADDLE_TPU_SERVING_DONATE")


def _pow2_ladder(lo, hi):
    out = []
    b = lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(out)


def serving_stats():
    """The ``serving.*`` counter family with its default keys
    materialized.  Monitoring processes should read
    ``paddle_tpu.inference.serving_stats`` / ``profiler.serving_stats``
    instead — same registry cells, no serving-stack import."""
    return dict(_stats_family())


def _stats_family():
    return metrics.stats_family("serving", {
        "prefill_compiles": 0, "decode_compiles": 0,
        "prefill_calls": 0, "decode_steps": 0,
        "requests_admitted": 0, "requests_completed": 0,
        "tokens_generated": 0, "queue_rejects": 0,
        "step_aborts": 0, "requests_aborted": 0,
        "requests_cancelled": 0,
        "standalone_compiles": 0})


class _StatsMirror:
    """SignatureLRU-compatible ``inc`` that routes through the engine's
    dual (global family + per-engine) counting."""

    def __init__(self, engine):
        self._engine = engine

    def inc(self, key, v=1):
        self._engine._inc(key, v)


class Request:
    """One generation request's lifecycle record.

    ``request_id`` is the request's STABLE identity: client-suppliable
    (any hashable — a router retrying across replicas reuses the same id
    so completions dedupe), auto-assigned a uuid4 hex otherwise.  It
    travels into ``serving_step`` / ``request_complete`` JSONL events
    and the latency-histogram labels, so telemetry from different
    replicas joins on it."""

    def __init__(self, prompt, max_new_tokens, eos_token=None,
                 request_id=None):
        self.id = request_id if request_id is not None else uuid.uuid4().hex
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.eos_token = eos_token
        self.tokens = []            # generated ids (python ints)
        self.logits = None          # per-token [V] rows when captured
        self.slot = None
        self.done = False
        self.failed = False         # aborted mid-step; re-queueable
        self.error = None           # the abort's diagnosis when failed
        self.finish_reason = None   # "length" | "eos"
        self.submit_t = time.perf_counter()
        self.finish_t = None

    @property
    def output(self):
        """prompt + generated ids as one int32 array."""
        return np.concatenate([self.prompt,
                               np.asarray(self.tokens, np.int32)])

    def latency(self):
        return (self.finish_t - self.submit_t) if self.done else None

    def reset_for_retry(self):
        """Scrub generation state so the SAME Request (same id, same
        limits) can be re-queued from scratch after a mid-step abort —
        greedy decoding makes the retry token-exact with a run that
        never failed."""
        self.tokens = []
        self.logits = None
        self.slot = None
        self.done = False
        self.failed = False
        self.error = None
        self.finish_reason = None
        self.finish_t = None
        return self


class ServingEngine:
    """Continuous-batching greedy decoder over a GPT functional core.

    ``model``: a ``models.gpt.GPT`` Layer, or a ``(params_pytree, cfg)``
    pair (raw jax arrays).  Knobs:

    * ``slots`` — in-flight sequence pool size (the decode batch).
    * ``max_len`` — per-slot KV capacity; admission requires
      ``len(prompt) + max_new_tokens <= max_len``.
    * ``seq_buckets`` / ``batch_buckets`` — the prefill shape ladder;
      total prefill executables are bounded by
      ``len(seq_buckets) * len(batch_buckets)``.
    * ``max_queue`` — bounded admission queue (default ``8 * slots``);
      beyond it :meth:`submit` raises :class:`ServingQueueFull`.
    * ``capture_logits`` — keep each request's per-token fp32 logit rows
      (parity tests / bench; costs a host fetch per step).

    Decoding is greedy (the parity contract with
    ``models.gpt.generate(temperature=0)``).
    """

    def __init__(self, model, *, slots=4, max_len=None, seq_buckets=None,
                 batch_buckets=DEFAULT_BATCH_BUCKETS, max_queue=None,
                 capture_logits=False, cache_dtype=None):
        import jax
        import jax.numpy as jnp
        self._jax, self._jnp = jax, jnp

        if isinstance(model, (tuple, list)) and len(model) == 2:
            params, cfg = model
        else:
            cfg = model.cfg
            from ..ops import dispatch as _dispatch
            params = _dispatch.unwrap(model._tree())
        self.cfg = cfg
        self.params = params

        self.slots = int(slots)
        self.max_len = int(max_len or cfg.max_seq_len)
        if self.max_len > cfg.max_seq_len:
            raise ValueError(f"max_len {self.max_len} exceeds "
                             f"cfg.max_seq_len {cfg.max_seq_len}")
        if seq_buckets is None:
            seq_buckets = _pow2_ladder(min(16, self.max_len), self.max_len)
        self.seq_buckets = tuple(sorted(int(s) for s in seq_buckets))
        if self.seq_buckets[-1] > self.max_len:
            raise ValueError(f"seq bucket {self.seq_buckets[-1]} exceeds "
                             f"max_len {self.max_len}")
        self.batch_buckets = tuple(sorted(int(b) for b in batch_buckets))
        self.max_queue = int(max_queue if max_queue is not None
                             else 8 * self.slots)
        self.capture_logits = bool(capture_logits)

        # a restart re-loads yesterday's executables (no-op without
        # PADDLE_JIT_CACHE_DIR)
        jax_compat.enable_persistent_cache()
        timeline.install_compile_hook()

        self._cache_dtype = cache_dtype
        cache = gpt.init_slot_cache(cfg, self.slots, self.max_len,
                                    dtype=cache_dtype)
        self._cache_k, self._cache_v = cache["k"], cache["v"]
        # host-side bookkeeping mirrors: authoritative for scheduling
        self._lens = np.zeros((self.slots,), np.int32)
        self._active = np.zeros((self.slots,), bool)
        self._last_tok = np.zeros((self.slots,), np.int32)
        self._slot_req = [None] * self.slots
        self._queue = collections.deque()

        self._stats = _stats_family()
        # the serving.* family is process-global (all engines share the
        # registry cells); _inc mirrors every count into THIS engine's
        # own dict, which stats() reports — a global-delta snapshot would
        # misattribute a coexisting engine's traffic
        self._counts = {k: 0 for k in self._stats}
        self._prefill = SignatureLRU(
            maxsize=4 * len(self.seq_buckets) * len(self.batch_buckets),
            stats=_StatsMirror(self), compile_key="prefill_compiles")
        self._decode_jit = None
        self._g_queue = metrics.gauge("serving.queue_depth")
        self._g_occ = metrics.gauge("serving.slot_occupancy")
        self._g_occ_peak = metrics.gauge("serving.slot_occupancy_peak")
        self._g_tps = metrics.gauge("serving.tokens_per_s")
        self._h_prefill = metrics.histogram("serving.prefill_s")
        self._h_decode = metrics.histogram("serving.decode_step_s")
        # a fleet replica labels its latency series with its replica id
        # (PADDLE_FLEET_REPLICA, set by the router) so per-replica
        # latency joins across the fleet's merged telemetry
        self._replica = os.environ.get("PADDLE_FLEET_REPLICA")
        self._h_req = metrics.histogram(
            "serving.request_latency_s",
            **({"replica": self._replica} if self._replica else {}))
        self._aborted = []          # mid-step abort victims, until taken
        self._admitting = []        # requests inside the current prefill
        self._finished_backlog = []  # finished, not yet handed to a caller
        self._tok_window = collections.deque(maxlen=64)  # (t, n) samples
        self._occ_peak = 0
        self._warming = False

    # ------------------------------------------------------------- intake
    _UNSET = object()

    def submit(self, prompt, max_new_tokens=_UNSET, eos_token=_UNSET,
               request_id=_UNSET):
        """Queue one request; returns its :class:`Request` handle.
        ``prompt`` is a token array (``max_new_tokens`` defaults to 16)
        or a prepared :class:`Request` — whose limits travel ON it, so
        passing them here too would be silently dropped and raises
        instead.  Raises :class:`ServingQueueFull` past ``max_queue``
        queued (the pool's in-flight slots don't count — they drain on
        their own)."""
        U = self._UNSET
        if isinstance(prompt, Request):
            if (max_new_tokens is not U or eos_token is not U
                    or request_id is not U):
                raise ValueError(
                    "submit(Request, ...) ignores per-call limits — set "
                    "max_new_tokens/eos_token/request_id on the Request "
                    "itself")
            req = prompt
            # latency is measured from ENQUEUE: a Request prepared long
            # before submission must not report its idle time as serving
            req.submit_t = time.perf_counter()
        else:
            req = Request(prompt,
                          16 if max_new_tokens is U else max_new_tokens,
                          None if eos_token is U else eos_token,
                          None if request_id is U else request_id)
        need = len(req.prompt) + req.max_new_tokens
        if need > self.max_len:
            raise ValueError(
                f"request needs {need} cache positions "
                f"(prompt {len(req.prompt)} + {req.max_new_tokens} new) "
                f"> max_len {self.max_len}")
        if len(req.prompt) > self.seq_buckets[-1]:
            raise ValueError(
                f"prompt length {len(req.prompt)} exceeds the largest "
                f"prefill bucket {self.seq_buckets[-1]}")
        if len(self._queue) >= self.max_queue:
            self._inc("queue_rejects")
            raise ServingQueueFull(
                f"queue depth {len(self._queue)} at max_queue "
                f"{self.max_queue}")
        self._queue.append(req)
        self._g_queue.set(len(self._queue))
        return req

    # ------------------------------------------------------- bucket maths
    def _seq_bucket(self, n):
        for b in self.seq_buckets:
            if n <= b:
                return b
        raise ValueError(f"no seq bucket fits prompt length {n}")

    def _batch_bucket(self, n):
        for b in self.batch_buckets:
            if n <= b:
                return b
        return self.batch_buckets[-1]

    # --------------------------------------------------------- executables
    def _build_prefill(self, b, s):
        """One prefill executable per (batch, seq) bucket: runs the causal
        forward over the padded prompts, scatters each row's K/V into its
        slot of the DONATED pool buffer, and samples each row's first
        token from the logits at its true last position."""
        jax, jnp = self._jax, self._jnp
        cfg = self.cfg

        cap = self.capture_logits

        def prefill(params, cache_k, cache_v, tokens, lens, slot_ids):
            fresh = gpt.init_cache(cfg, b, s, dtype=cache_k.dtype)
            logits, filled = gpt.forward_cached(params, tokens, cfg, fresh)
            for r in range(b):          # b is static: unrolled scatter
                cache_k = jax.lax.dynamic_update_slice(
                    cache_k, filled["k"][:, r:r + 1],
                    (0, slot_ids[r], 0, 0, 0))
                cache_v = jax.lax.dynamic_update_slice(
                    cache_v, filled["v"][:, r:r + 1],
                    (0, slot_ids[r], 0, 0, 0))
            idx = jnp.clip(lens - 1, 0, s - 1)
            last = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1)[:, 0]      # [b, V]
            first_tok = jnp.argmax(last, -1).astype(jnp.int32)
            # a fp32 [b, V] output nobody reads is dead HBM traffic on
            # the hot path — only materialize it when capturing
            if cap:
                return cache_k, cache_v, first_tok, last
            return cache_k, cache_v, first_tok

        donate = (1, 2) if _donation_enabled() else ()
        return jax.jit(prefill, donate_argnums=donate)

    def _build_decode(self):
        jax, jnp = self._jax, self._jnp
        cfg = self.cfg

        cap = self.capture_logits

        def decode(params, cache_k, cache_v, lens, toks, active):
            cache = {"k": cache_k, "v": cache_v, "len": lens}
            logits, cache = gpt.decode_step_slots(params, toks, cfg, cache,
                                                  active)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            if cap:
                return cache["k"], cache["v"], nxt, logits
            return cache["k"], cache["v"], nxt

        donate = (1, 2) if _donation_enabled() else ()
        return jax.jit(decode, donate_argnums=donate)

    # ----------------------------------------------------------- scheduling
    def _free_slots(self):
        return [i for i in range(self.slots) if not self._active[i]]

    def _admit(self):
        """Move queued requests into free slots, one prefill wave per
        contiguous same-seq-bucket run (padded to the batch ladder).
        Requests finishing DURING admission — the prefill's first
        sampled token can already satisfy ``max_new_tokens=1`` or hit
        ``eos_token`` — land on the finished backlog like any other."""
        jnp = self._jnp
        while self._queue and not self._active.all():
            free = self._free_slots()
            group, sbucket = [], None
            while (self._queue and len(group) < len(free)
                   and len(group) < self.batch_buckets[-1]):
                nxt_b = self._seq_bucket(len(self._queue[0].prompt))
                if sbucket is None:
                    sbucket = nxt_b
                elif nxt_b != sbucket:
                    break           # next wave picks it up
                group.append(self._queue.popleft())
            if not group:
                break
            bbucket = self._batch_bucket(len(group))
            toks = np.zeros((bbucket, sbucket), np.int32)
            lens = np.ones((bbucket,), np.int32)   # pad rows: len 1
            slot_ids = np.zeros((bbucket,), np.int32)
            scratch = free[0]       # pad rows scatter over a row that a
            for r, req in enumerate(group):        # real row rewrites
                toks[r, :len(req.prompt)] = req.prompt
                lens[r] = len(req.prompt)
                slot_ids[r] = free[r]
                req.slot = free[r]
            for r in range(len(group), bbucket):
                slot_ids[r] = scratch
            if len(group) < bbucket:
                # a pad row writing AFTER a real row would clobber that
                # slot: scatter pads first (loop order in the executable
                # is row order), i.e. pads must come first.  Rows are
                # written in order r=0..b-1, so point pads at the scratch
                # slot and ensure the real row for that slot comes later.
                order = list(range(len(group), bbucket)) \
                    + list(range(len(group)))
                toks = toks[order]
                lens = lens[order]
                slot_ids = slot_ids[order]
                group_rows = {id(req): order.index(r)
                              for r, req in enumerate(group)}
            else:
                group_rows = {id(req): r for r, req in enumerate(group)}

            # visible to _abort_inflight: these requests left the queue
            # but are not in _slot_req yet — a prefill failure must mark
            # them re-queueable too, not silently lose them
            self._admitting = group
            fn = self._prefill.get(
                (bbucket, sbucket),
                lambda: self._build_prefill(bbucket, sbucket))
            t0 = time.perf_counter()
            with timeline.span("serving.prefill", batch=bbucket,
                               seq=sbucket):
                out = fn(self.params, self._cache_k, self._cache_v,
                         jnp.asarray(toks), jnp.asarray(lens),
                         jnp.asarray(slot_ids))
            if self.capture_logits:
                self._cache_k, self._cache_v, first_tok, last_logits = out
                logits_np = np.asarray(last_logits)
            else:
                self._cache_k, self._cache_v, first_tok = out
                logits_np = None
            self._inc("prefill_calls")
            first_np = np.asarray(first_tok)
            for req in group:
                r = group_rows[id(req)]
                s = req.slot
                self._lens[s] = len(req.prompt)
                self._active[s] = True
                self._slot_req[s] = req
                self._append_token(req, int(first_np[r]),
                                   logits_np[r] if logits_np is not None
                                   else None)
                self._last_tok[s] = int(first_np[r])
                self._inc("requests_admitted")
                # not during warmup: the quiet counters don't advance
                # there, so a step/request-scoped fault would see the
                # same index forever and fire at boot
                if _faults.active() and not self._warming:
                    _faults.replica_kill_check(
                        request=self._counts["requests_admitted"])
            self._admitting = []
            if not self._warming:
                self._h_prefill.observe(time.perf_counter() - t0)
        self._g_queue.set(len(self._queue))
        occ = int(self._active.sum())
        self._g_occ.set(occ)
        if not self._warming:
            self._occ_peak = max(self._occ_peak, occ)
            if occ > self._g_occ_peak.value:
                self._g_occ_peak.set(occ)

    def _append_token(self, req, tok, logits_row):
        req.tokens.append(tok)
        if self.capture_logits:
            if req.logits is None:
                req.logits = []
            req.logits.append(np.asarray(logits_row, np.float32))
        self._inc("tokens_generated")
        if not self._warming:
            self._tok_window.append((time.perf_counter(), 1))
        if (req.eos_token is not None and tok == req.eos_token):
            self._finish(req, "eos")
        elif len(req.tokens) >= req.max_new_tokens:
            self._finish(req, "length")

    def _finish(self, req, reason):
        req.done = True
        req.finish_reason = reason
        req.finish_t = time.perf_counter()
        # completions ride a backlog drained by step()/take_finished():
        # a request finishing inside a step that LATER raises must still
        # reach the caller (the fleet worker reports it to the router) —
        # returning step-local lists would drop it with the exception
        self._finished_backlog.append(req)
        if not self._warming:
            self._h_req.observe(req.finish_t - req.submit_t)
            if timeline.telemetry_dir():
                timeline.emit({"event": "request_complete",
                               "request_id": str(req.id),
                               "replica": self._replica,
                               "latency_s": round(
                                   req.finish_t - req.submit_t, 6),
                               "tokens": len(req.tokens),
                               "finish_reason": reason})
        if req.slot is not None:
            s = req.slot
            self._active[s] = False
            self._slot_req[s] = None
            gpt.reset_slots(self._lens, s)
        self._inc("requests_completed")

    # ------------------------------------------------------------- driving
    def step(self):
        """One engine iteration: admit from the queue into free slots,
        then one slot-batched decode step.  Returns the requests that
        FINISHED this iteration (their slots are already free — the next
        ``step()`` re-admits from the queue: continuous batching).

        If the step raises mid-flight (device error, injected
        ``engine_error`` fault), every in-flight request is ABORTED
        rather than leaked: its slot is freed, the KV pool is rebuilt
        (a failed donated dispatch may have consumed the buffers), and
        the request is marked ``failed``/re-queueable and parked in
        :meth:`take_aborted` — occupancy recovers instead of pinning
        dead slots forever.  The original exception still propagates;
        requests that COMPLETED before the failure stay on the finished
        backlog and come back from the next ``step()`` /
        :meth:`take_finished` — a crash after a completion never
        un-completes it."""
        try:
            self._step_inner()
        except Exception as e:
            self._abort_inflight(e)
            raise
        return self.take_finished()

    def take_finished(self):
        """Drain the finished-request backlog (normally what ``step()``
        just returned; after a step that RAISED, the requests that
        completed before the failure)."""
        out, self._finished_backlog = self._finished_backlog, []
        return out

    def _abort_inflight(self, err):
        """Free every slot and mark the victims re-queueable (the
        slot-leak fix): in-flight requests AND any mid-admission group
        whose prefill failed after leaving the queue."""
        aborted = [r for r in self._slot_req if r is not None]
        aborted += [r for r in self._admitting
                    if r not in aborted and not r.done]
        self._admitting = []
        detail = f"{type(err).__name__}: {err}"
        for req in aborted:
            req.failed = True
            req.error = detail
            req.slot = None
        self._active[:] = False
        self._lens[:] = 0
        self._slot_req = [None] * self.slots
        # rebuild the donated KV pool: the failed dispatch may have
        # consumed (donated) the old buffers, and whatever it scattered
        # is untrusted anyway — every victim restarts from its prompt
        cache = gpt.init_slot_cache(self.cfg, self.slots, self.max_len,
                                    dtype=self._cache_dtype)
        self._cache_k, self._cache_v = cache["k"], cache["v"]
        self._g_occ.set(0)
        if aborted:
            self._inc("step_aborts")
            self._inc("requests_aborted", len(aborted))
            self._aborted.extend(aborted)
        return aborted

    def take_aborted(self):
        """Drain the requests aborted by failed steps since the last
        call — the fleet worker re-queues these (each already
        ``reset_for_retry()``-able; ids are stable so the router
        dedupes)."""
        out, self._aborted = self._aborted, []
        return out

    def cancel(self, request_id):
        """Remove a QUEUED request by id (deadline/cancel path); returns
        the Request or None.  An in-flight request runs to completion —
        callers dedupe/discard its completion by id."""
        for req in self._queue:
            if req.id == request_id:
                self._queue.remove(req)
                self._g_queue.set(len(self._queue))
                self._inc("requests_cancelled")
                return req
        return None

    def _step_inner(self):
        self._admit()
        if not self._active.any():
            return
        finished = []        # this decode wave's, for the step event
        jnp = self._jnp
        if _faults.active() and not self._warming:
            _faults.engine_step_error(self._counts["decode_steps"] + 1)
            _faults.replica_kill_check(
                step=self._counts["decode_steps"] + 1)
        if self._decode_jit is None:
            self._decode_jit = self._build_decode()
            self._inc("decode_compiles")
        t0 = time.perf_counter()
        with timeline.span("serving.decode_step",
                           active=int(self._active.sum())):
            out = self._decode_jit(
                self.params, self._cache_k, self._cache_v,
                jnp.asarray(self._lens), jnp.asarray(self._last_tok),
                jnp.asarray(self._active))
        if self.capture_logits:
            self._cache_k, self._cache_v, nxt, logits = out
            logits_np = np.asarray(logits)
        else:
            self._cache_k, self._cache_v, nxt = out
            logits_np = None
        self._inc("decode_steps")
        nxt_np = np.asarray(nxt)
        for s in range(self.slots):
            if not self._active[s]:
                continue
            req = self._slot_req[s]
            self._lens[s] += 1
            self._append_token(req, int(nxt_np[s]),
                               logits_np[s] if logits_np is not None
                               else None)
            self._last_tok[s] = int(nxt_np[s])
            if req.done:
                finished.append(req)
        dt = time.perf_counter() - t0
        if not self._warming:
            self._h_decode.observe(dt)
        self._g_occ.set(int(self._active.sum()))
        self._update_tps()
        if not self._warming and timeline.telemetry_dir():
            timeline.emit({"event": "serving_step",
                           "active": int(self._active.sum()),
                           "queue": len(self._queue),
                           "decode_s": round(dt, 6),
                           "finished": len(finished),
                           # stable ids: telemetry joins across replicas
                           "finished_ids": [str(r.id) for r in finished]})

    def _tps_value(self):
        """Tokens/s over THIS engine's recent-sample window (0.0 until
        two samples exist)."""
        if len(self._tok_window) < 2:
            return 0.0
        t0 = self._tok_window[0][0]
        t1 = self._tok_window[-1][0]
        if t1 <= t0:
            return 0.0
        return round(sum(c for _, c in self._tok_window) / (t1 - t0), 3)

    def _update_tps(self):
        v = self._tps_value()
        if v:
            self._g_tps.set(v)

    def run(self, max_steps=None):
        """Drive :meth:`step` until the queue and every slot drain.
        Returns all requests finished during the run."""
        out = []
        steps = 0
        while self._queue or self._active.any():
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return out

    def warmup(self, max_new_tokens=2):
        """Compile every ladder executable BEFORE taking traffic: for
        each (batch, seq) bucket pair, run a wave of dummy requests
        shaped exactly to it, plus the decode step.  After this, steady
        serving issues zero new XLA compiles no matter which buckets
        requests land in — and with ``PADDLE_JIT_CACHE_DIR`` set, a
        restarted server's warmup is pure cache reload.  The synthetic
        wave is kept OUT of the traffic telemetry (latency histograms,
        tokens/s window, occupancy peak, request/step counters) — only
        the compile counters record it — so a consumer's percentiles
        describe real requests, not compile time.  Returns the number
        of prefill executables compiled."""
        before = self._counts["prefill_compiles"]
        self._warming = True
        # back-pressure is for traffic, not boot: a deliberately small
        # max_queue must not reject the warmup waves (each wave needs its
        # whole group queued at once so it prefills as ONE batch rung)
        real_max_queue = self.max_queue
        self.max_queue = max(real_max_queue, self.slots,
                             self.batch_buckets[-1])
        try:
            lo = 1                  # smallest prompt length in this rung
            for s in self.seq_buckets:
                # a legal request lands in this rung iff even its
                # SHORTEST prompt (lo) leaves room for one generated
                # token; longer warmup prompts shrink max_new_tokens
                # rather than sliding down a rung (prompt 15 / max_new 1
                # on a max_len-16 ladder must still precompile the top
                # bucket)
                mnt = min(max_new_tokens, self.max_len - lo)
                if mnt < 1:
                    continue        # rung unreachable by any admission
                n = min(s, self.max_len - mnt)
                lo = s + 1
                prev = 0
                for b in self.batch_buckets:
                    # smallest group size that pads to bucket b; a rung
                    # no group can reach (its floor exceeds the pool)
                    # stays cold
                    wave = prev + 1
                    prev = b
                    if wave > self.slots:
                        continue
                    for _ in range(wave):
                        self.submit(np.ones((n,), np.int32), mnt)
                    self.run()
        finally:
            self._warming = False
            self.max_queue = real_max_queue
        return self._counts["prefill_compiles"] - before

    def reset_occupancy_peak(self):
        """Restart THIS engine's slot-occupancy high-water mark (e.g.
        after a warmup wave, so a measured run's peak reflects ITS
        traffic).  The shared ``serving.slot_occupancy_peak`` gauge is a
        process-wide monotone max — lowering it here would erase a
        coexisting engine's recorded peak."""
        self._occ_peak = int(self._active.sum())

    def generate(self, prompts, max_new_tokens=16, eos_token=None):
        """Batch convenience: submit every prompt, run to drain, return
        the per-prompt generated-token lists in submission order.
        Batches larger than ``max_queue`` are absorbed by stepping the
        engine between submissions (back-pressure is for ONLINE callers
        who can shed; a batch caller just wants the work done)."""
        reqs = []
        for p in prompts:
            while (len(self._queue) >= self.max_queue
                   and (self._queue or self._active.any())):
                self.step()         # drain room instead of rejecting
            reqs.append(self.submit(p, max_new_tokens, eos_token))
        self.run()
        return [r.tokens for r in reqs]

    # --------------------------------------------------------------- views
    # traffic counters a warmup wave must not inflate; compile counters
    # stay live (compiling executables is exactly what warmup reports)
    _WARMUP_QUIET = frozenset((
        "prefill_calls", "decode_steps", "requests_admitted",
        "requests_completed", "tokens_generated"))

    def _inc(self, key, v=1):
        """Count into the process-global serving.* registry family AND
        this engine's own dict — :meth:`stats` reads the latter, so a
        coexisting engine's traffic is never misattributed."""
        if self._warming and key in self._WARMUP_QUIET:
            return
        self._stats.inc(key, v)
        self._counts[key] = self._counts.get(key, 0) + v

    def stats(self):
        """THIS engine's serving.* counters + live gauges, one dict.
        The process-global family (all engines pooled) is
        :func:`serving_stats`."""
        out = dict(self._counts)
        out["queue_depth"] = len(self._queue)
        out["slot_occupancy"] = int(self._active.sum())
        out["slot_occupancy_peak"] = self._occ_peak
        # from the engine-local sample window, NOT the shared gauge — a
        # coexisting engine's throughput must not show up here
        out["tokens_per_s"] = self._tps_value()
        return out
