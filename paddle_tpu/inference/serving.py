"""High-throughput serving engine: continuous batching over a slot-pooled
KV cache (ISSUE 5 tentpole).

The reference serves frozen programs through a request-at-a-time predictor
(ref: paddle/fluid/inference/api/analysis_predictor.cc) — fine for CNNs,
hopeless for autoregressive decoding, where request-level batching wastes
most of the batch on padding and parks finished sequences until the
slowest one drains.  This engine is the Orca/vLLM-shaped redesign:

* **slots, not batches** — a fixed pool of ``slots`` decode lanes backed
  by ONE shared ``[L, slots, max_len, nh, hd]`` KV buffer with a per-slot
  fill length (models/gpt.py::init_slot_cache).  Every iteration one
  jitted, **buffer-donated** decode step (models/gpt.py::decode_step_slots)
  advances all in-flight sequences a token; a finished sequence's slot is
  handed to the next queued request immediately — no drain barrier, no
  padding rows beyond the pool size.  The decode executable's signature
  never changes, so requests churning through slots cost ZERO retraces.
* **bucketed prefill** — prompts are padded to a ``(batch, seq)`` shape
  ladder and prefilled through per-bucket executables (cached in a
  :class:`~paddle_tpu.ops.dispatch.SignatureLRU`, the dispatch cache's
  keying discipline), so compile count is bounded by the ladder size no
  matter how many distinct prompt lengths arrive.  Each prefill executable
  also scatters its K/V rows straight into the donated slot buffer and
  returns the first sampled token — one XLA program per admission wave.
* **persistent compiles** — ``PADDLE_JIT_CACHE_DIR`` (via
  framework/jax_compat.py::enable_persistent_cache) makes a server restart
  reload yesterday's executables instead of re-running XLA.

Telemetry rides the PR-4 registry under ``serving.*``: queue depth and
slot occupancy gauges, prefill/decode/request latency histograms,
tokens/s, and compile counters the bench asserts on.

:class:`PagedServingEngine` (ISSUE 8, bottom of this module) replaces
the slot-contiguous pool with a block-table paged KV cache — fixed-size
pages, per-slot page tables, shared-prefix page reuse, chunked prefill —
while keeping every invariant above (one donated decode executable,
token-exact greedy parity, bounded prefill compiles).
"""
from __future__ import annotations

import collections
import hashlib
import itertools
import os
import time
import uuid

import numpy as np

from ..framework import compile_cache as _cc
from ..framework import jax_compat
from ..models import gpt
from ..observability import metrics, timeline, tracing
from ..testing import faults as _faults

DEFAULT_BATCH_BUCKETS = (1, 2, 4)

# per-process engine instance ids: serving_step / request_complete
# events stamp "engine" so multi-engine processes (tests, spec decode's
# draft+target pair) stay distinguishable in one rank's JSONL
_ENGINE_IDS = itertools.count()


class ServingQueueFull(RuntimeError):
    """submit() back-pressure: the bounded admission queue is at
    ``max_queue`` — callers must retry/shed, exactly like a 429."""


def _donation_enabled():
    """Donate the slot KV buffers into prefill/decode executables
    (in-place update, no second cache-sized allocation).  Same contract
    as the fused optimizer step: ``PADDLE_TPU_SERVING_DONATE`` 0/1
    forces, auto skips CPU (whose donation path only warns)."""
    return jax_compat.donation_enabled("PADDLE_TPU_SERVING_DONATE")


# the shape-ladder maths live in the unified compile layer now
_pow2_ladder = _cc.pow2_ladder


def serving_stats():
    """The ``serving.*`` counter family with its default keys
    materialized.  Monitoring processes should read
    ``paddle_tpu.inference.serving_stats`` / ``profiler.serving_stats``
    instead — same registry cells, no serving-stack import."""
    return dict(_stats_family())


def _stats_family():
    return metrics.stats_family("serving", {
        "prefill_compiles": 0, "decode_compiles": 0,
        "prefill_calls": 0, "decode_steps": 0,
        "requests_admitted": 0, "requests_completed": 0,
        "tokens_generated": 0, "queue_rejects": 0,
        "step_aborts": 0, "requests_aborted": 0,
        "requests_cancelled": 0,
        "standalone_compiles": 0,
        # paged-KV family (PagedServingEngine; zero on slot engines)
        "prefill_chunks": 0, "prefix_page_hits": 0,
        "prefix_page_misses": 0, "cow_copies": 0, "preemptions": 0,
        # quantized-serving family (ISSUE 9): quantized matmuls executed,
        # KV bytes the int8 pool saved vs the same pool at compute
        # dtype, and fused dequant kernel INSTANTIATIONS — the inc
        # fires at trace time, once per kernel per compiled executable,
        # so it answers "did the Pallas path engage in what XLA built?"
        # not "how many steps ran" (0 off-TPU: the lax fallback serves)
        "quant_matmuls": 0, "kv_quant_bytes_saved": 0,
        "dequant_kernel_calls": 0,
        # speculative-decoding family (SpeculativeServingEngine,
        # ISSUE 13; zero on non-speculative engines): candidates the
        # drafter proposed, how many of those the verify accepted /
        # rejected, and verify dispatches (each commits accepted+1
        # tokens: the longest accepted draft prefix plus the bonus
        # token the verify's own logits supply)
        "drafted_tokens": 0, "accepted_tokens": 0,
        "rejected_tokens": 0, "spec_steps": 0,
        "spec_draft_compiles": 0,
        # prefill/decode disaggregation family (ISSUE 15; zero on
        # unified engines): KV page extractions shipped off a prefill
        # engine, injections landed on a decode engine, the bytes that
        # crossed, and the extract/inject executable acquisitions
        "kv_extracts": 0, "kv_injects": 0, "kv_handoff_bytes": 0,
        "handoff_compiles": 0,
        # fleet-scale KV tiering family (ISSUE 17; zero without a host
        # tier): device pages spilled into the host-RAM tier and their
        # bytes, pages faulted BACK into the device pool on a prefix
        # hit, no-prefill fault-back admissions, and host entries whose
        # content-hash verification REJECTED them (corrupt bytes are
        # dropped and the request re-prefills — never served)
        "pages_spilled": 0, "spill_bytes": 0,
        "pages_faulted_back": 0, "fault_backs": 0,
        "fault_back_rejects": 0})


def _legacy_counter(engine, key):
    """compile_cache ``legacy_inc`` adapter: an executable acquisition
    (build OR artifact load) counts into the engine's dual (global
    serving.* family + per-engine) legacy counter — the aliased view
    the bench's ladder/compile bounds read."""
    def inc(event):
        if event == "build":
            engine._inc(key)
    return inc


class Request:
    """One generation request's lifecycle record.

    ``request_id`` is the request's STABLE identity: client-suppliable
    (any hashable — a router retrying across replicas reuses the same id
    so completions dedupe), auto-assigned a uuid4 hex otherwise.  It
    travels into ``serving_step`` / ``request_complete`` JSONL events
    and the latency-histogram labels, so telemetry from different
    replicas joins on it."""

    def __init__(self, prompt, max_new_tokens, eos_token=None,
                 request_id=None):
        self.id = request_id if request_id is not None else uuid.uuid4().hex
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.eos_token = eos_token
        self.tokens = []            # generated ids (python ints)
        self.logits = None          # per-token [V] rows when captured
        self.slot = None
        self.preemptions = 0        # page-exhaustion evictions survived
        # prefill/decode disaggregation (ISSUE 15): a prefill-only
        # request finishes at admission with its prompt's KV pages
        # extracted onto ``kv_payload`` (reason "prefill_done"); an
        # injected request carries the shipped pages in ``_inject``
        # until the decode engine scatters them into its pool
        self.prefill_only = False
        self.kv_payload = None      # host arrays, one per pool operand
        self._inject = None         # shipped pages awaiting injection
        self._inject_tok = None     # the prefill's first sampled token
        # speculative engine's per-row pending-draft state (ISSUE 13):
        # committed tokens the draft model has not ingested yet (None
        # until the spec engine activates the row).  MUST be scrubbed on
        # retry — a preempted-then-retried request re-prefills the draft
        # cache from its prompt, and stale ctx would double-feed tokens
        self.pending_draft = None
        self.done = False
        self.failed = False         # aborted mid-step; re-queueable
        self.error = None           # the abort's diagnosis when failed
        self.finish_reason = None   # "length" | "eos"
        self.submit_t = time.perf_counter()
        self.finish_t = None
        # distributed tracing (ISSUE 19): the router mints this at
        # admission and ships it on every RPC hop; engine-side span
        # events carry it so cross-process assembly stitches one
        # lifecycle.  Direct (non-fleet) engine use mints its own when
        # tracing is on — a fleet worker overwrites it with the
        # router's id before any span event fires.
        self.trace_id = tracing.mint() if tracing.enabled() else None

    @property
    def output(self):
        """prompt + generated ids as one int32 array."""
        return np.concatenate([self.prompt,
                               np.asarray(self.tokens, np.int32)])

    def latency(self):
        return (self.finish_t - self.submit_t) if self.done else None

    def reset_for_retry(self):
        """Scrub generation state so the SAME Request (same id, same
        limits) can be re-queued from scratch after a mid-step abort or
        a page-exhaustion preemption — greedy decoding makes the retry
        token-exact with a run that never failed.  ``preemptions``
        survives on purpose (it is the retry's audit trail)."""
        self.tokens = []
        self.logits = None
        self.slot = None
        self.pending_draft = None
        self.kv_payload = None      # a retried prefill re-extracts
        self.done = False
        self.failed = False
        self.error = None
        self.finish_reason = None
        self.finish_t = None
        return self


class ServingEngine:
    """Continuous-batching greedy decoder over a GPT functional core.

    ``model``: a ``models.gpt.GPT`` Layer, or a ``(params_pytree, cfg)``
    pair (raw jax arrays).  Knobs:

    * ``slots`` — in-flight sequence pool size (the decode batch).
    * ``max_len`` — per-slot KV capacity; admission requires
      ``len(prompt) + max_new_tokens <= max_len``.
    * ``seq_buckets`` / ``batch_buckets`` — the prefill shape ladder;
      total prefill executables are bounded by
      ``len(seq_buckets) * len(batch_buckets)``.
    * ``max_queue`` — bounded admission queue (default ``8 * slots``);
      beyond it :meth:`submit` raises :class:`ServingQueueFull`.
    * ``capture_logits`` — keep each request's per-token fp32 logit rows
      (parity tests / bench; costs a host fetch per step).
    * ``quant`` — weight-only quantization mode (``"int8"``,
      ``"int8_dynamic"``, ``"fp8"``; see models/gpt.py::quantize_params):
      the param pytree is quantized once at construction and every
      executable runs its matmuls through the fused dequant path.
      Accuracy is a budget, not exact parity — gate on the bench's
      logit-error check.

    Decoding is greedy (the parity contract with
    ``models.gpt.generate(temperature=0)``).
    """

    def __init__(self, model, *, slots=4, max_len=None, seq_buckets=None,
                 batch_buckets=DEFAULT_BATCH_BUCKETS, max_queue=None,
                 capture_logits=False, cache_dtype=None, quant=None,
                 tp=None, pp=None):
        import jax
        import jax.numpy as jnp
        self._jax, self._jnp = jax, jnp

        if isinstance(model, (tuple, list)) and len(model) == 2:
            params, cfg = model
        else:
            cfg = model.cfg
            from ..ops import dispatch as _dispatch
            params = _dispatch.unwrap(model._tree())
        self.cfg = cfg
        # weight-only quantization (ISSUE 9): the param pytree is
        # quantized ONCE here — every executable built below closes over
        # int8/fp8 weights + scales as ordinary pytree operands, and
        # models/gpt.py::block_apply routes their matmuls through the
        # fused dequant path.  Orthogonal to the paged engine's
        # kv_dtype: quant shrinks the weights, kv_dtype the KV pool.
        self.quant = quant
        self._kv_dtype = None          # the paged subclass may set int8
        if quant is not None:
            params = gpt.quantize_params(params, quant)
        # tensor-parallel serving (ISSUE 15): ``tp`` (env fallback
        # PADDLE_SERVE_TP) places the params with the megatron
        # column/row rules from distributed/auto/rules.py and shards
        # the KV pool's head axis over a 1-D 'tp' mesh; the executables
        # below stay the same jnp programs — GSPMD partitions them from
        # the operand shardings, so a model whose fp32 weights exceed
        # one device serves with each rank holding ~1/tp of the bytes.
        if tp is None:
            tp = os.environ.get("PADDLE_SERVE_TP") or 1
        self._tp = int(tp)
        if self._tp < 1:
            raise ValueError(f"tp must be >= 1, got {self._tp}")
        # pipeline-stage serving (ISSUE 20): ``pp`` (env fallback
        # PADDLE_SERVE_PP) adds a leading 'pp' mesh axis — the stacked
        # layer axis of every block param AND of the KV pools splits
        # across stages, and the paged executables run the 1F1B
        # microbatch schedule (distributed/auto/pipeline.py) inside the
        # one donated step, handing activations between stages with
        # ppermute.
        if pp is None:
            pp = os.environ.get("PADDLE_SERVE_PP") or 1
        self._pp = int(pp)
        if self._pp < 1:
            raise ValueError(f"pp must be >= 1, got {self._pp}")
        if self._pp > 1 and type(self) is ServingEngine:
            # the 1F1B stage loop lives in the paged builders only; the
            # slot engine has no pp path (and silently ignoring the
            # knob would void the per-stage memory claim)
            raise ValueError("pp > 1 needs the paged engine — "
                             "use PagedServingEngine(pp=...)")
        self._mesh = None
        self._param_specs = None
        if self._tp > 1 or self._pp > 1:
            if self._tp > 1 and cfg.num_heads % self._tp:
                raise ValueError(
                    f"num_heads {cfg.num_heads} must divide by tp "
                    f"{self._tp} — the KV pool shards on the head axis")
            if (self._tp > 1 and getattr(cfg, "moe_experts", 0)
                    and cfg.moe_experts % self._tp):
                raise ValueError(
                    f"moe_experts {cfg.moe_experts} must divide by tp "
                    f"{self._tp} — expert MLPs shard WHOLE over the tp "
                    "axis (expert parallelism)")
            if self._pp > 1 and cfg.num_layers % self._pp:
                raise ValueError(
                    f"num_layers {cfg.num_layers} must divide by pp "
                    f"{self._pp} — stages take contiguous equal layer "
                    "ranges (distributed/auto/pipeline.py)")
            self._mesh = gpt.serving_mesh(self._tp, pp=self._pp)
            params, self._param_specs = gpt.shard_params_for_serving(
                params, cfg, self._mesh)
        self._kv_spec = gpt.kv_pool_spec(self._mesh)
        self.params = params

        self.slots = int(slots)
        self.max_len = int(max_len or cfg.max_seq_len)
        if self.max_len > cfg.max_seq_len:
            raise ValueError(f"max_len {self.max_len} exceeds "
                             f"cfg.max_seq_len {cfg.max_seq_len}")
        if seq_buckets is None:
            seq_buckets = _pow2_ladder(min(16, self.max_len), self.max_len)
        self.seq_buckets = tuple(sorted(int(s) for s in seq_buckets))
        if self.seq_buckets[-1] > self.max_len:
            raise ValueError(f"seq bucket {self.seq_buckets[-1]} exceeds "
                             f"max_len {self.max_len}")
        self.batch_buckets = tuple(sorted(int(b) for b in batch_buckets))
        self.max_queue = int(max_queue if max_queue is not None
                             else 8 * self.slots)
        self.capture_logits = bool(capture_logits)
        # speculative-decoding identity (the spec subclass overrides;
        # part of the fleet numeric/behavior contract attestation)
        self.spec_mode = None
        self.spec_k = None

        # a restart re-loads yesterday's executables (no-op without
        # PADDLE_JIT_CACHE_DIR)
        jax_compat.enable_persistent_cache()
        timeline.install_compile_hook()

        self._cache_dtype = cache_dtype
        self._rebuild_cache()
        # host-side bookkeeping mirrors: authoritative for scheduling
        self._lens = np.zeros((self.slots,), np.int32)
        self._active = np.zeros((self.slots,), bool)
        self._last_tok = np.zeros((self.slots,), np.int32)
        self._slot_req = [None] * self.slots
        self._queue = collections.deque()

        self._stats = _stats_family()
        # the serving.* family is process-global (all engines share the
        # registry cells); _inc mirrors every count into THIS engine's
        # own dict, which stats() reports — a global-delta snapshot would
        # misattribute a coexisting engine's traffic
        self._counts = {k: 0 for k in self._stats}
        self._prefill = _cc.site(
            "serving.prefill",
            maxsize=4 * len(self.seq_buckets) * len(self.batch_buckets),
            legacy_inc=_legacy_counter(self, "prefill_compiles"))
        self._decode_site = _cc.site("serving.decode", maxsize=4)
        self._decode_jit = None
        self._g_queue = metrics.gauge("serving.queue_depth")
        self._g_occ = metrics.gauge("serving.slot_occupancy")
        self._g_occ_peak = metrics.gauge("serving.slot_occupancy_peak")
        self._g_tps = metrics.gauge("serving.tokens_per_s")
        self._h_prefill = metrics.histogram("serving.prefill_s")
        self._h_decode = metrics.histogram("serving.decode_step_s")
        # a fleet replica labels its latency series with its replica id
        # (PADDLE_FLEET_REPLICA, set by the router) so per-replica
        # latency joins across the fleet's merged telemetry
        self._replica = os.environ.get("PADDLE_FLEET_REPLICA")
        self._engine_id = next(_ENGINE_IDS)
        self._h_req = metrics.histogram(
            "serving.request_latency_s",
            **({"replica": self._replica} if self._replica else {}))
        self._aborted = []          # mid-step abort victims, until taken
        self._admitting = []        # requests inside the current prefill
        self._finished_backlog = []  # finished, not yet handed to a caller
        self._tok_window = collections.deque(maxlen=64)  # (t, n) samples
        self._occ_peak = 0
        self._warming = False

    def _rebuild_cache(self):
        """(Re)allocate the KV pool — called at construction and by
        :meth:`_abort_inflight` (a failed donated dispatch consumed the
        old buffers).  The paged subclass overrides this with the page
        pool + allocator reset."""
        cache = gpt.init_slot_cache(self.cfg, self.slots, self.max_len,
                                    dtype=self._cache_dtype,
                                    mesh=self._mesh)
        self._cache_k, self._cache_v = cache["k"], cache["v"]

    # ------------------------------------------------------------- intake
    _UNSET = object()

    def submit(self, prompt, max_new_tokens=_UNSET, eos_token=_UNSET,
               request_id=_UNSET):
        """Queue one request; returns its :class:`Request` handle.
        ``prompt`` is a token array (``max_new_tokens`` defaults to 16)
        or a prepared :class:`Request` — whose limits travel ON it, so
        passing them here too would be silently dropped and raises
        instead.  Raises :class:`ServingQueueFull` past ``max_queue``
        queued (the pool's in-flight slots don't count — they drain on
        their own)."""
        U = self._UNSET
        if isinstance(prompt, Request):
            if (max_new_tokens is not U or eos_token is not U
                    or request_id is not U):
                raise ValueError(
                    "submit(Request, ...) ignores per-call limits — set "
                    "max_new_tokens/eos_token/request_id on the Request "
                    "itself")
            req = prompt
            # latency is measured from ENQUEUE: a Request prepared long
            # before submission must not report its idle time as serving
            req.submit_t = time.perf_counter()
        else:
            req = Request(prompt,
                          16 if max_new_tokens is U else max_new_tokens,
                          None if eos_token is U else eos_token,
                          None if request_id is U else request_id)
        need = len(req.prompt) + req.max_new_tokens
        if need > self.max_len:
            raise ValueError(
                f"request needs {need} cache positions "
                f"(prompt {len(req.prompt)} + {req.max_new_tokens} new) "
                f"> max_len {self.max_len}")
        if req.prefill_only and not getattr(self, "_handoff", False):
            raise ValueError(
                "prefill-only admission needs a "
                "PagedServingEngine(kv_handoff=True) — this engine has "
                "no page-extraction path")
        self._check_prompt(req)
        # the bound covers EVERY admission queue (_queued_total: the
        # paged engine's injection queue included) — the gauge, stats()
        # and this check must agree on what "queued" means
        if self._queued_total() >= self.max_queue:
            self._inc("queue_rejects")
            raise ServingQueueFull(
                f"queue depth {self._queued_total()} at max_queue "
                f"{self.max_queue}")
        self._queue.append(req)
        self._g_queue.set(self._queued_total())
        return req

    def _check_prompt(self, req):
        """Reject prompts the engine can NEVER serve (a named fast
        failure beats bouncing them forever).  The paged subclass
        relaxes the bucket bound for chunk-eligible prompts."""
        if len(req.prompt) > self.seq_buckets[-1]:
            raise ValueError(
                f"prompt length {len(req.prompt)} exceeds the largest "
                f"prefill bucket {self.seq_buckets[-1]}")

    # ------------------------------------------------------- bucket maths
    def _seq_bucket(self, n):
        for b in self.seq_buckets:
            if n <= b:
                return b
        raise ValueError(f"no seq bucket fits prompt length {n}")

    def _batch_bucket(self, n):
        for b in self.batch_buckets:
            if n <= b:
                return b
        return self.batch_buckets[-1]

    # --------------------------------------------------------- executables
    _n_cache = 2          # KV pool operands per executable (paged: 2|4)

    def _donate(self, first=1):
        """donate_argnums for an executable whose KV pool operands sit
        at positions ``first .. first + n_cache - 1`` — the ONE place
        the donation signature is computed, so the site keys, the AOT
        stable keys and the built executables can never disagree."""
        return (tuple(range(first, first + self._n_cache))
                if _donation_enabled() else ())

    def _aot_sig(self):
        """Cross-process-stable identity of every executable this engine
        builds: the model config plus every knob that changes program
        SHAPES or structure (never param values — params are operands,
        so artifacts are shared across seeds and checkpoints).  The
        artifact store additionally stamps jax version + backend."""
        import dataclasses
        cfg = dataclasses.asdict(self.cfg)
        cfgs = ",".join(f"{k}={cfg[k]}" for k in sorted(cfg))
        return (f"cfg[{cfgs}]/quant={self.quant}/kv={self._kv_dtype}"
                f"/cap={int(self.capture_logits)}/slots={self.slots}"
                f"/max_len={self.max_len}/cdt={self._cache_dtype}"
                f"/donate={int(_donation_enabled())}/tp={self._tp}"
                f"/pp={self._pp}")

    def _aot_key(self, kind, **extra):
        ex = "".join(f"/{k}={v}" for k, v in sorted(extra.items()))
        return f"serving/{kind}/{self._aot_sig()}{ex}"

    def _mesh_key(self):
        """Mesh-topology part folded into every compile-cache key
        (ISSUE 15): a sharded executable on a different mesh is a
        different program.  None on single-device engines, so their
        keys are byte-identical to the pre-TP era."""
        if self._mesh is None:
            return None
        devs = self._mesh.devices.reshape(-1)
        if self._pp > 1:
            return ("pp", self._pp, "tp", self._tp,
                    devs[0].platform, len(devs))
        # pp == 1 keys stay byte-identical to the pre-pp era so
        # yesterday's tp artifacts survive the field's introduction
        return ("tp", self._tp, devs[0].platform, len(devs))

    def _topology(self):
        """The artifact-header device-topology attestation: the AOT
        store rejects (as stale, rebuilt) a sharded executable
        deserialized onto a mismatched mesh; single-device artifacts
        carry None and stay valid across the field's introduction."""
        mk = self._mesh_key()
        return None if mk is None else "/".join(str(p) for p in mk)

    def _constrain_cache(self, arrs):
        """Pin KV-pool outputs to the pool sharding inside the jitted
        builders, so every executable's output sharding provably equals
        its input's.  Donated dispatches already guarantee it (aliased
        buffers share a layout); on the non-donated CPU path GSPMD
        propagation USUALLY agrees — this makes it an invariant, not a
        habit.  No-op on single-device engines."""
        if self._mesh is None:
            return tuple(arrs)
        return tuple(jax_compat.with_sharding_constraint(
            a, self._mesh, self._kv_spec) for a in arrs)

    def param_bytes_per_device(self):
        """Bytes of the (possibly tp-sharded) param pytree each device
        actually pins — the bench's serves-past-one-device proof."""
        from ..distributed.auto import rules
        return rules.bytes_per_device(self.params)

    def _cache_operands(self):
        """The KV pool arrays in executable-operand order (the paged
        subclass overrides with the page pool, + scales on int8)."""
        return (self._cache_k, self._cache_v)

    @staticmethod
    def _bytes_on(dev, tree):
        """Bytes of ``tree`` pinned on ONE device: the shard that lives
        there for sharded leaves, the full copy for replicated ones."""
        import jax
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                for sh in shards:
                    if sh.device == dev:
                        total += (sh.data.size
                                  * np.dtype(sh.data.dtype).itemsize)
            else:
                total += leaf.size * np.dtype(leaf.dtype).itemsize
        return total

    def stage_bytes(self):
        """Per-pipeline-stage memory proof: what ONE device of each
        stage row actually pins — params + KV pool (the int8 scale
        arrays ride both: weight scales in the param tree, KV scales in
        the cache operands) — so the over-budget bench assertion is
        honest about what each device holds.  A pp==1 engine reports
        one stage covering everything."""
        from ..distributed.auto import rules
        if self._mesh is None or self._pp == 1:
            return [{"params": rules.bytes_per_device(self.params),
                     "kv": rules.bytes_per_device(
                         list(self._cache_operands()))}]
        grid = self._mesh.devices        # [pp, tp]
        out = []
        for s in range(self._pp):
            dev = grid[s].reshape(-1)[0]
            out.append({
                "params": self._bytes_on(dev, self.params),
                "kv": self._bytes_on(dev, list(self._cache_operands()))})
        return out

    def _build_prefill(self, b, s):
        """One prefill executable per (batch, seq) bucket: runs the causal
        forward over the padded prompts, scatters each row's K/V into its
        slot of the DONATED pool buffer, and samples each row's first
        token from the logits at its true last position."""
        jax, jnp = self._jax, self._jnp
        cfg = self.cfg

        cap = self.capture_logits

        def prefill(params, cache_k, cache_v, tokens, lens, slot_ids):
            fresh = gpt.init_cache(cfg, b, s, dtype=cache_k.dtype)
            logits, filled = gpt.forward_cached(params, tokens, cfg, fresh)
            for r in range(b):          # b is static: unrolled scatter
                cache_k = jax.lax.dynamic_update_slice(
                    cache_k, filled["k"][:, r:r + 1],
                    (0, slot_ids[r], 0, 0, 0))
                cache_v = jax.lax.dynamic_update_slice(
                    cache_v, filled["v"][:, r:r + 1],
                    (0, slot_ids[r], 0, 0, 0))
            cache_k, cache_v = self._constrain_cache((cache_k, cache_v))
            idx = jnp.clip(lens - 1, 0, s - 1)
            last = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1)[:, 0]      # [b, V]
            first_tok = jnp.argmax(last, -1).astype(jnp.int32)
            # a fp32 [b, V] output nobody reads is dead HBM traffic on
            # the hot path — only materialize it when capturing
            if cap:
                return cache_k, cache_v, first_tok, last
            return cache_k, cache_v, first_tok

        donate = (1, 2) if _donation_enabled() else ()
        return jax.jit(prefill, donate_argnums=donate)

    def _build_decode(self):
        jax, jnp = self._jax, self._jnp
        cfg = self.cfg

        cap = self.capture_logits

        def decode(params, cache_k, cache_v, lens, toks, active):
            cache = {"k": cache_k, "v": cache_v, "len": lens}
            logits, cache = gpt.decode_step_slots(params, toks, cfg, cache,
                                                  active)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            ck, cv = self._constrain_cache((cache["k"], cache["v"]))
            if cap:
                return ck, cv, nxt, logits
            return ck, cv, nxt

        donate = (1, 2) if _donation_enabled() else ()
        return jax.jit(decode, donate_argnums=donate)

    # ----------------------------------------------------------- scheduling
    def _free_slots(self):
        return [i for i in range(self.slots) if not self._active[i]]

    def _admit(self):
        """Move queued requests into free slots, one prefill wave per
        contiguous same-seq-bucket run (padded to the batch ladder).
        Requests finishing DURING admission — the prefill's first
        sampled token can already satisfy ``max_new_tokens=1`` or hit
        ``eos_token`` — land on the finished backlog like any other."""
        jnp = self._jnp
        while self._queue and not self._active.all():
            free = self._free_slots()
            group, sbucket = [], None
            while (self._queue and len(group) < len(free)
                   and len(group) < self.batch_buckets[-1]):
                nxt_b = self._seq_bucket(len(self._queue[0].prompt))
                if sbucket is None:
                    sbucket = nxt_b
                elif nxt_b != sbucket:
                    break           # next wave picks it up
                group.append(self._queue.popleft())
            if not group:
                break
            bbucket = self._batch_bucket(len(group))
            toks = np.zeros((bbucket, sbucket), np.int32)
            lens = np.ones((bbucket,), np.int32)   # pad rows: len 1
            slot_ids = np.zeros((bbucket,), np.int32)
            scratch = free[0]       # pad rows scatter over a row that a
            for r, req in enumerate(group):        # real row rewrites
                toks[r, :len(req.prompt)] = req.prompt
                lens[r] = len(req.prompt)
                slot_ids[r] = free[r]
                req.slot = free[r]
            for r in range(len(group), bbucket):
                slot_ids[r] = scratch
            if len(group) < bbucket:
                # a pad row writing AFTER a real row would clobber that
                # slot: scatter pads first (loop order in the executable
                # is row order), i.e. pads must come first.  Rows are
                # written in order r=0..b-1, so point pads at the scratch
                # slot and ensure the real row for that slot comes later.
                order = list(range(len(group), bbucket)) \
                    + list(range(len(group)))
                toks = toks[order]
                lens = lens[order]
                slot_ids = slot_ids[order]
                group_rows = {id(req): order.index(r)
                              for r, req in enumerate(group)}
            else:
                group_rows = {id(req): r for r, req in enumerate(group)}

            # visible to _abort_inflight: these requests left the queue
            # but are not in _slot_req yet — a prefill failure must mark
            # them re-queueable too, not silently lose them
            self._admitting = group
            if tracing.enabled() and not self._warming:
                for req in group:
                    tracing.event(
                        "queue_wait", trace_id=req.trace_id,
                        request_id=req.id, batch=bbucket, seq=sbucket,
                        wait_s=round(
                            time.perf_counter() - req.submit_t, 6))
            donate = self._donate()
            operands = (self.params, self._cache_k, self._cache_v,
                        jnp.asarray(toks), jnp.asarray(lens),
                        jnp.asarray(slot_ids))
            fn = self._prefill.get(
                _cc.make_key(bbucket, sbucket, donate=donate,
                             mesh=self._mesh_key()),
                lambda: self._build_prefill(bbucket, sbucket),
                stable_key=self._aot_key("prefill", b=bbucket, s=sbucket),
                example_args=operands, topology=self._topology())
            t0 = time.perf_counter()
            with timeline.span("serving.prefill", batch=bbucket,
                               seq=sbucket):
                out = fn(*operands)
            if self.capture_logits:
                self._cache_k, self._cache_v, first_tok, last_logits = out
                # capture_logits debug mode: the caller asked for host
                # logits; off by default
                # ptl: disable-next=PTL004 -- capture_logits debug mode
                logits_np = np.asarray(last_logits)
            else:
                self._cache_k, self._cache_v, first_tok = out
                logits_np = None
            self._inc("prefill_calls")
            self._count_quant_matmuls()
            # sampled-first-token readback: the one designed sync point
            # of the prefill wave
            # ptl: disable-next=PTL004 -- sampled-first-token readback
            first_np = np.asarray(first_tok)
            for req in group:
                r = group_rows[id(req)]
                s = req.slot
                self._lens[s] = len(req.prompt)
                self._active[s] = True
                self._slot_req[s] = req
                self._append_token(req, int(first_np[r]),
                                   logits_np[r] if logits_np is not None
                                   else None)
                self._last_tok[s] = int(first_np[r])
                self._inc("requests_admitted")
                # not during warmup: the quiet counters don't advance
                # there, so a step/request-scoped fault would see the
                # same index forever and fire at boot
                if _faults.active() and not self._warming:
                    _faults.replica_kill_check(
                        request=self._counts["requests_admitted"])
            self._admitting = []
            if not self._warming:
                self._h_prefill.observe(time.perf_counter() - t0)
        self._g_queue.set(self._queued_total())
        occ = int(self._active.sum())
        self._g_occ.set(occ)
        if not self._warming:
            self._occ_peak = max(self._occ_peak, occ)
            if occ > self._g_occ_peak.value:
                self._g_occ_peak.set(occ)

    def _append_token(self, req, tok, logits_row):
        req.tokens.append(tok)
        if self.capture_logits:
            if req.logits is None:
                req.logits = []
            # logits_row is the already-synced host copy (logits_np
            # slice), not a device value
            # ptl: disable-next=PTL004 -- already-synced host copy
            req.logits.append(np.asarray(logits_row, np.float32))
        self._inc("tokens_generated")
        if not self._warming:
            self._tok_window.append((time.perf_counter(), 1))
        if (req.eos_token is not None and tok == req.eos_token):
            self._finish(req, "eos")
        elif len(req.tokens) >= req.max_new_tokens:
            self._finish(req, "length")

    def _append_tokens(self, req, toks, logits_rows=None):
        """Multi-token commit (ISSUE 13): append an accepted speculative
        window's tokens in order, stopping at the first finishing token
        (eos / length — the device-side commit math already truncates
        there, so the guard is defensive).  ``logits_rows`` is the
        already-synced [W, V] host block when capturing.  Returns how
        many were appended."""
        n = 0
        for i, tok in enumerate(toks):
            self._append_token(req, int(tok),
                               logits_rows[i] if logits_rows is not None
                               else None)
            n += 1
            if req.done:
                break
        return n

    def _finish(self, req, reason):
        req.done = True
        req.finish_reason = reason
        req.finish_t = time.perf_counter()
        # completions ride a backlog drained by step()/take_finished():
        # a request finishing inside a step that LATER raises must still
        # reach the caller (the fleet worker reports it to the router) —
        # returning step-local lists would drop it with the exception
        self._finished_backlog.append(req)
        if not self._warming:
            self._h_req.observe(req.finish_t - req.submit_t)
            if timeline.telemetry_dir():
                timeline.emit({"event": "request_complete",
                               "request_id": str(req.id),
                               "replica": self._replica,
                               # per-process total order + emitter id
                               # (ISSUE 19): trace assembly sorts on seq
                               # at equal timestamps
                               "seq": tracing.seq(),
                               "engine": self._engine_id,
                               "latency_s": round(
                                   req.finish_t - req.submit_t, 6),
                               "tokens": len(req.tokens),
                               "finish_reason": reason})
            # distinct names per phase outcome: trace assembly uses the
            # FIRST "completion" as the decode-end boundary, so the
            # disagg prefill leg's finish must not shadow it
            tracing.event("prefill_done" if reason == "prefill_done"
                          else "completion",
                          trace_id=req.trace_id, request_id=req.id,
                          finish_reason=reason, tokens=len(req.tokens),
                          engine=self._engine_id)
        if req.slot is not None:
            s = req.slot
            self._active[s] = False
            self._slot_req[s] = None
            gpt.reset_slots(self._lens, s)
        self._inc("requests_completed")

    # ------------------------------------------------------------- driving
    def step(self):
        """One engine iteration: admit from the queue into free slots,
        then one slot-batched decode step.  Returns the requests that
        FINISHED this iteration (their slots are already free — the next
        ``step()`` re-admits from the queue: continuous batching).

        If the step raises mid-flight (device error, injected
        ``engine_error`` fault), every in-flight request is ABORTED
        rather than leaked: its slot is freed, the KV pool is rebuilt
        (a failed donated dispatch may have consumed the buffers), and
        the request is marked ``failed``/re-queueable and parked in
        :meth:`take_aborted` — occupancy recovers instead of pinning
        dead slots forever.  The original exception still propagates;
        requests that COMPLETED before the failure stay on the finished
        backlog and come back from the next ``step()`` /
        :meth:`take_finished` — a crash after a completion never
        un-completes it."""
        try:
            self._step_inner()
        except Exception as e:
            self._abort_inflight(e)
            raise
        return self.take_finished()

    def take_finished(self):
        """Drain the finished-request backlog (normally what ``step()``
        just returned; after a step that RAISED, the requests that
        completed before the failure)."""
        out, self._finished_backlog = self._finished_backlog, []
        return out

    def _abort_inflight(self, err):
        """Free every slot and mark the victims re-queueable (the
        slot-leak fix): in-flight requests AND any mid-admission group
        whose prefill failed after leaving the queue."""
        aborted = [r for r in self._slot_req if r is not None]
        aborted += [r for r in self._admitting
                    if r not in aborted and not r.done]
        self._admitting = []
        detail = f"{type(err).__name__}: {err}"
        for req in aborted:
            req.failed = True
            req.error = detail
            req.slot = None
        self._active[:] = False
        self._lens[:] = 0
        self._slot_req = [None] * self.slots
        # rebuild the donated KV pool: the failed dispatch may have
        # consumed (donated) the old buffers, and whatever it scattered
        # is untrusted anyway — every victim restarts from its prompt
        self._rebuild_cache()
        self._g_occ.set(0)
        if aborted:
            self._inc("step_aborts")
            self._inc("requests_aborted", len(aborted))
            self._aborted.extend(aborted)
            # incident flight dump: last-hop ring + the victims' ids —
            # the postmortem names who was in flight, not just a counter
            tracing.dump("engine_abort",
                         inflight=[r.id for r in aborted],
                         extra={"error": detail[:400],
                                "engine": self._engine_id})
        return aborted

    def take_aborted(self):
        """Drain the requests aborted by failed steps since the last
        call — the fleet worker re-queues these (each already
        ``reset_for_retry()``-able; ids are stable so the router
        dedupes)."""
        out, self._aborted = self._aborted, []
        return out

    def active_request_ids(self):
        """Ids this engine still OWNS (queued, mid-admission, or
        holding a decode slot) — the fleet worker's readopt re-hello
        claims exactly these after a router restart.  Parked abort
        victims are excluded on purpose: they need a re-queue, not a
        claim, and the relaunched router's journal replay re-queues
        every unclaimed id anyway."""
        ids = [str(r.id) for r in self._queue]
        ids += [str(r.id) for r in self._admitting if not r.done]
        ids += [str(r.id) for r in self._slot_req if r is not None]
        seen = set()
        return [i for i in ids if not (i in seen or seen.add(i))]

    def cancel(self, request_id):
        """Remove a QUEUED request by id (deadline/cancel path); returns
        the Request or None.  An in-flight request runs to completion —
        callers dedupe/discard its completion by id."""
        for req in self._queue:
            if req.id == request_id:
                self._queue.remove(req)
                self._g_queue.set(self._queued_total())
                self._inc("requests_cancelled")
                return req
        return None

    def _step_inner(self):
        self._admit()
        if not self._active.any():
            return
        finished = []        # this decode wave's, for the step event
        jnp = self._jnp
        if _faults.active() and not self._warming:
            _faults.engine_step_error(self._counts["decode_steps"] + 1)
            _faults.replica_kill_check(
                step=self._counts["decode_steps"] + 1)
        operands = (self.params, self._cache_k, self._cache_v,
                    jnp.asarray(self._lens), jnp.asarray(self._last_tok),
                    jnp.asarray(self._active))
        if self._decode_jit is None:
            donate = self._donate()
            self._decode_jit = self._decode_site.get(
                _cc.make_key("decode", donate=donate,
                             mesh=self._mesh_key()),
                self._build_decode,
                stable_key=self._aot_key("decode"),
                example_args=operands, topology=self._topology())
            self._inc("decode_compiles")
        t0 = time.perf_counter()
        with timeline.span("serving.decode_step",
                           active=int(self._active.sum())):
            out = self._decode_jit(*operands)
        if self.capture_logits:
            self._cache_k, self._cache_v, nxt, logits = out
            # ptl: disable-next=PTL004 -- capture_logits debug mode readback
            logits_np = np.asarray(logits)
        else:
            self._cache_k, self._cache_v, nxt = out
            logits_np = None
        self._inc("decode_steps")
        self._count_quant_matmuls()
        # sampled-token readback: THE designed device->host sync of the
        # decode loop (tokens must reach clients)
        # ptl: disable-next=PTL004 -- sampled-token readback
        nxt_np = np.asarray(nxt)
        for s in range(self.slots):
            if not self._active[s]:
                continue
            req = self._slot_req[s]
            self._lens[s] += 1
            self._append_token(req, int(nxt_np[s]),
                               logits_np[s] if logits_np is not None
                               else None)
            self._last_tok[s] = int(nxt_np[s])
            if req.done:
                finished.append(req)
        dt = time.perf_counter() - t0
        if not self._warming:
            self._h_decode.observe(dt)
        self._g_occ.set(int(self._active.sum()))
        self._update_tps()
        if not self._warming and timeline.telemetry_dir():
            timeline.emit({"event": "serving_step",
                           "active": int(self._active.sum()),
                           "queue": len(self._queue),
                           "decode_s": round(dt, 6),
                           "finished": len(finished),
                           # per-process total order + emitter (ISSUE 19)
                           "seq": tracing.seq(),
                           "engine": self._engine_id,
                           "replica": self._replica,
                           # stable ids: telemetry joins across replicas
                           "finished_ids": [str(r.id) for r in finished]})
        if tracing.enabled() and not self._warming:
            for r in finished:
                tracing.event("decode_iter", trace_id=r.trace_id,
                              request_id=r.id, iters=len(r.tokens),
                              decode_s=round(dt, 6),
                              engine=self._engine_id)

    def _tps_value(self):
        """Tokens/s over THIS engine's recent-sample window (0.0 until
        two samples exist)."""
        if len(self._tok_window) < 2:
            return 0.0
        t0 = self._tok_window[0][0]
        t1 = self._tok_window[-1][0]
        if t1 <= t0:
            return 0.0
        return round(sum(c for _, c in self._tok_window) / (t1 - t0), 3)

    def _update_tps(self):
        v = self._tps_value()
        if v:
            self._g_tps.set(v)

    def _queued_total(self):
        """Requests waiting for admission — the one definition the
        queue-depth gauge AND stats() read (the paged subclass adds its
        injection queue, so a decode-role replica's queued handoffs are
        never reported as an idle engine)."""
        return len(self._queue)

    def _busy(self):
        """Work left to drive?  (The paged subclass adds its
        mid-chunked-prefill jobs, which hold slots without being decode-
        active yet.)"""
        return bool(self._queue) or bool(self._active.any())

    def run(self, max_steps=None):
        """Drive :meth:`step` until the queue and every slot drain.
        Returns all requests finished during the run."""
        out = []
        steps = 0
        while self._busy():
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return out

    # ------------------------------------------------- AOT artifact boot
    def _aot_covered(self):
        """Artifact-warm boot (ISSUE 14): the set of (b, s) prefill
        rungs whose serialized artifacts VALIDATE (header + digest +
        jax/backend match — a merely-existing stale artifact from a
        shared dir after a jax upgrade must not count) — those rungs
        SKIP their dummy compile wave, and the executables load lazily
        at first use (an artifact load is a deserialization, not an XLA
        compile, so the zero-steady-state-compiles invariant holds
        either way).  Empty when no store is active or the CORE
        executables (decode; subclasses add theirs) have no valid
        artifacts — a partial store must not skip the wave that would
        have compiled the missing piece (the degradation contract)."""
        if _cc.artifact_dir() is None or not _cc.aot_available():
            return set()
        if not self._aot_has_core():
            return set()
        return {(b, s) for s in self.seq_buckets
                for b in self.batch_buckets
                if _cc.artifact_ready(
                    self._aot_key("prefill", b=b, s=s),
                    topology=self._topology())}

    def _aot_has_core(self):
        """Do the non-ladder executables the warmup waves would compile
        have artifacts?  (decode here; paged adds nothing — its
        chunk/copy warm blocks gate themselves; the speculative engine
        needs verify + draft.)"""
        return _cc.artifact_ready(self._aot_key("decode"),
                                  topology=self._topology())

    def warmup(self, max_new_tokens=2):
        """Compile every ladder executable BEFORE taking traffic: for
        each (batch, seq) bucket pair, run a wave of dummy requests
        shaped exactly to it, plus the decode step.  After this, steady
        serving issues zero new XLA compiles no matter which buckets
        requests land in — and with ``PADDLE_JIT_CACHE_DIR`` set, a
        restarted server's warmup is pure cache reload.  With
        ``PADDLE_AOT_CACHE_DIR`` holding artifacts, warmup degenerates
        further: preloaded rungs are deserialized executables and their
        dummy waves are SKIPPED — zero compiles, near-zero execution
        (the fleet cold-start path).  The synthetic wave is kept OUT of
        the traffic telemetry (latency histograms, tokens/s window,
        occupancy peak, request/step counters) — only the compile
        counters record it — so a consumer's percentiles describe real
        requests, not compile time.  Returns the number of prefill
        executables compiled (artifact loads included — they count as
        acquisitions)."""
        before = self._counts["prefill_compiles"]
        preloaded = self._aot_covered()
        self._warming = True
        # back-pressure is for traffic, not boot: a deliberately small
        # max_queue must not reject the warmup waves (each wave needs its
        # whole group queued at once so it prefills as ONE batch rung)
        real_max_queue = self.max_queue
        self.max_queue = max(real_max_queue, self.slots,
                             self.batch_buckets[-1])
        try:
            lo = 1                  # smallest prompt length in this rung
            for s in self.seq_buckets:
                # a legal request lands in this rung iff even its
                # SHORTEST prompt (lo) leaves room for one generated
                # token; longer warmup prompts shrink max_new_tokens
                # rather than sliding down a rung (prompt 15 / max_new 1
                # on a max_len-16 ladder must still precompile the top
                # bucket)
                mnt = min(max_new_tokens, self.max_len - lo)
                if mnt < 1:
                    continue        # rung unreachable by any admission
                n = self._warmup_wave_len(lo, s, mnt)
                lo = s + 1
                if n is None:
                    continue        # rung unreachable via this path
                prev = 0
                for b in self.batch_buckets:
                    # smallest group size that pads to bucket b; a rung
                    # no group can reach (its floor exceeds the pool)
                    # stays cold
                    wave = prev + 1
                    prev = b
                    if wave > self.slots:
                        continue
                    if (b, s) in preloaded:
                        continue    # artifact-loaded: nothing to compile
                    for _ in range(wave):
                        self.submit(np.ones((n,), np.int32), mnt)
                    self.run()
        finally:
            self._warming = False
            self.max_queue = real_max_queue
        return self._counts["prefill_compiles"] - before

    def _warmup_wave_len(self, lo, s, mnt):
        """Warmup prompt length that lands in bucket rung ``s`` (whose
        shortest admissible prompt is ``lo``), or None if no wave
        prompt can reach the rung.  The paged subclass caps this at
        ``prefill_chunk`` — longer prompts divert to the chunked path
        and would leave the rung cold."""
        return min(s, self.max_len - mnt)

    def reset_occupancy_peak(self):
        """Restart THIS engine's slot-occupancy high-water mark (e.g.
        after a warmup wave, so a measured run's peak reflects ITS
        traffic).  The shared ``serving.slot_occupancy_peak`` gauge is a
        process-wide monotone max — lowering it here would erase a
        coexisting engine's recorded peak."""
        self._occ_peak = int(self._active.sum())

    def generate(self, prompts, max_new_tokens=16, eos_token=None):
        """Batch convenience: submit every prompt, run to drain, return
        the per-prompt generated-token lists in submission order.
        Batches larger than ``max_queue`` are absorbed by stepping the
        engine between submissions (back-pressure is for ONLINE callers
        who can shed; a batch caller just wants the work done)."""
        reqs = []
        for p in prompts:
            while (len(self._queue) >= self.max_queue
                   and self._busy()):
                self.step()         # drain room instead of rejecting
            reqs.append(self.submit(p, max_new_tokens, eos_token))
        self.run()
        return [r.tokens for r in reqs]

    # --------------------------------------------------------------- views
    # traffic counters a warmup wave must not inflate; compile counters
    # stay live (compiling executables is exactly what warmup reports)
    _WARMUP_QUIET = frozenset((
        "prefill_calls", "decode_steps", "requests_admitted",
        "requests_completed", "tokens_generated",
        "prefill_chunks", "prefix_page_hits", "prefix_page_misses",
        "cow_copies", "preemptions", "quant_matmuls",
        "drafted_tokens", "accepted_tokens", "rejected_tokens",
        "spec_steps", "kv_extracts", "kv_injects", "kv_handoff_bytes",
        "pages_spilled", "spill_bytes", "pages_faulted_back",
        "fault_backs", "fault_back_rejects"))

    def _count_quant_matmuls(self):
        """One model forward = 4 quantized matmuls per layer (qkv, proj,
        fc1, fc2) when the weights are quantized — counted next to every
        prefill/chunk/decode dispatch so ``serving.quant_matmuls``
        tracks the quantized executables actually running."""
        if self.quant:
            self._inc("quant_matmuls", 4 * self.cfg.num_layers)

    def _inc(self, key, v=1):
        """Count into the process-global serving.* registry family AND
        this engine's own dict — :meth:`stats` reads the latter, so a
        coexisting engine's traffic is never misattributed."""
        if self._warming and key in self._WARMUP_QUIET:
            return
        self._stats.inc(key, v)
        self._counts[key] = self._counts.get(key, 0) + v

    def stats(self):
        """THIS engine's serving.* counters + live gauges, one dict.
        The process-global family (all engines pooled) is
        :func:`serving_stats`."""
        out = dict(self._counts)
        out["queue_depth"] = self._queued_total()
        out["slot_occupancy"] = int(self._active.sum())
        out["slot_occupancy_peak"] = self._occ_peak
        # from the engine-local sample window, NOT the shared gauge — a
        # coexisting engine's throughput must not show up here
        out["tokens_per_s"] = self._tps_value()
        # the numeric contract (fleet routing/hello attests on these: a
        # mixed fp32/int8 fleet must never cross-route)
        out["quant"] = self.quant
        out["kv_dtype"] = self._kv_dtype
        out["spec_mode"] = self.spec_mode
        out["tp"] = self._tp
        out["pp"] = self._pp
        if self._pp > 1:
            out["stage_bytes"] = self.stage_bytes()
        out.update(self._kv_accounting())
        return out

    def _kv_accounting(self):
        """KV-memory accounting (bench.py --serving's kv block): a
        slot-contiguous pool RESERVES its full footprint whether or not
        slots are filled — that over-reservation is exactly what the
        paged subclass's override shrinks."""
        held = int(self._lens.sum())
        return {"kv_bytes_reserved": int(self._cache_k.nbytes
                                         + self._cache_v.nbytes),
                "kv_tokens_held": held}


# --------------------------------------------------------------------------
# host-RAM KV page tier (ISSUE 17 tentpole)
# --------------------------------------------------------------------------

class _HostKVTier:
    """Byte-bounded LRU of spilled KV pages in host RAM — the tier
    UNDER the device page pool.  Entries are keyed by the pager's
    content key and stamped with a blake2b over their exact bytes
    (salted with the engine's numeric contract): a fault-back serves an
    entry only after re-verifying the stamp, so torn host memory can
    never reach the device pool — the per-shard page-byte-determinism
    invariant extends through the tier."""

    def __init__(self, limit_bytes, hash_key=""):
        self.limit = int(limit_bytes)
        self.hash_key = str(hash_key)
        self._ent = collections.OrderedDict()  # key -> [arrays, stamp, t]
        self.bytes = 0
        self.inserts = 0
        self.lru_evictions = 0

    def __len__(self):
        return len(self._ent)

    def __contains__(self, key):
        return key in self._ent

    def _stamp(self, arrays):
        h = hashlib.blake2b(digest_size=16)
        h.update(self.hash_key.encode())
        for a in arrays:
            h.update(np.ascontiguousarray(a).tobytes())
        return h.hexdigest()

    def put(self, key, arrays):
        """Insert (or refresh) a spilled page's host copy: one array
        per pool operand, stamped NOW.  Oldest entries fall off the LRU
        until the byte bound holds again."""
        old = self._ent.pop(key, None)
        if old is not None:
            self.bytes -= sum(int(a.nbytes) for a in old[0])
        nbytes = sum(int(a.nbytes) for a in arrays)
        self._ent[key] = [list(arrays), self._stamp(arrays),
                          time.perf_counter()]
        self.bytes += nbytes
        self.inserts += 1
        while self.bytes > self.limit and len(self._ent) > 1:
            _, (arrs, _stamp, _t) = self._ent.popitem(last=False)
            self.bytes -= sum(int(a.nbytes) for a in arrs)
            self.lru_evictions += 1

    def fetch(self, key):
        """``(arrays, age_s)`` for a hash-verified entry (refreshed to
        LRU-newest), ``None`` when absent, or the string ``"corrupt"``
        when present but failing verification — the entry is dropped on
        the spot (bad KV is never served, and never re-tried)."""
        ent = self._ent.get(key)
        if ent is None:
            return None
        arrays, stamp, t = ent
        if self._stamp(arrays) != stamp:
            self._ent.pop(key)
            self.bytes -= sum(int(a.nbytes) for a in arrays)
            return "corrupt"
        self._ent.move_to_end(key)
        return arrays, time.perf_counter() - t

    def corrupt(self, key):
        """Testing hook (the ``host_tier_corrupt`` fault): flip one
        byte of the stored copy AFTER its stamp was taken, so the next
        :meth:`fetch` exercises the reject path."""
        ent = self._ent.get(key)
        if ent is None:
            return
        a = ent[0][0]
        flat = a.view(np.uint8).reshape(-1)
        flat[0] ^= 0xFF

    def digests(self, limit=64):
        """Compact digests of the FULL-page chains resident in the
        tier (the host half of the replica's routing sketch)."""
        from .kv_pager import short_digest
        out = []
        for key in self._ent:
            d = short_digest(key)
            if d is not None:
                out.append(d)
        return out[-int(limit):]


# --------------------------------------------------------------------------
# paged engine (ISSUE 8 tentpole)
# --------------------------------------------------------------------------

class PagedServingEngine(ServingEngine):
    """Continuous batching over a **block-table paged KV cache**: the
    contiguous-per-slot pool is replaced by ``num_pages`` fixed
    ``page_size``-token pages plus a per-slot page table
    (inference/kv_pager.py), so the HBM a request pins tracks its
    LENGTH, not ``max_len`` — at a fixed KV byte budget the paged pool
    admits several times the concurrency of the slot pool.  On top:

    * **shared-prefix reuse** (``prefix_cache=True``) — prompt pages are
      content-hashed; a request repeating an earlier system prompt
      re-acquires the same physical pages (zero new allocations, the
      smoke's attested "prefix hit"), released prompt pages are retained
      LRU for future hits, and divergence is copy-on-write.
    * **chunked prefill** (``prefill_chunk=N``) — prompts longer than
      ``N`` are admitted in ``N``-token pieces, ONE piece per engine
      iteration, so in-flight decodes keep producing tokens while a
      long admission trickles in instead of stalling behind one big
      prefill dispatch.  All chunks share one executable (the position
      offset is a traced scalar).
    * the PR-5 invariants survive: ONE buffer-donated jitted decode
      step forever (``decode_compiles == 1``; the page table, write
      coordinates and lengths are traced operands, so churn never
      changes the signature) and token-exact greedy parity with
      ``models.gpt.generate``.

    Attention gathers K/V through the table via
    ops/pallas/paged_attn.py — a Pallas kernel that DMAs exactly the
    referenced pages on TPU, and a pure-lax gather with *identical
    math* to the slot engine's masked attention elsewhere (CPU tier-1).

    Pool exhaustion is never a stall: the NEWEST request is preempted —
    pages freed, request re-queued from its prompt (named in telemetry
    as ``page_exhaustion``, counted in ``preemptions``, stamped on
    ``Request.preemptions``) — and greedy decoding makes its eventual
    retry token-exact.

    * **quantized KV** (``kv_dtype="int8"``, ISSUE 9) — the page pool
      stores K/V int8 with per-position-per-head fp32 scale arrays
      alongside (models/gpt.py::init_paged_cache_quant): prefill and
      chunk scatters quantize on write, decode attention dequantizes on
      read (in-kernel on TPU: ops/pallas/paged_attn.py::
      paged_attention_quant).  ~4x the tokens per KV byte; COW copies
      page+scale pairs; the prefix hash is salted with the numeric
      contract so int8 pages never alias fp pages.  Composes with
      ``quant=`` (weight-only int8/fp8 executables) — together they are
      the quantized serving path the bench gates on an accuracy budget.

    Constraints: ``max_len`` must be a page multiple (seq buckets are
    rounded up to page multiples), and ``prefill_chunk`` must divide
    ``max_len`` and fit inside the largest prefill bucket."""

    def __init__(self, model, *, page_size=16, num_pages=None,
                 prefix_cache=True, prefill_chunk=None, kv_dtype=None,
                 kv_handoff=False, host_tier_mb=None, **kw):
        from .kv_pager import KVPager, PagesExhausted  # noqa: F401
        self._KVPager, self._PagesExhausted = KVPager, PagesExhausted
        self._page_size = int(page_size)
        # host-RAM page tier (ISSUE 17): evicted prefix pages spill
        # their bytes (hash-stamped) into a byte-bounded host LRU, and
        # a later prefix hit on the spilled chain faults them back
        # through the donated inject executable WITHOUT re-prefilling.
        # 0 MB (the default) disables the tier entirely.
        if host_tier_mb is None:
            try:
                host_tier_mb = float(
                    os.environ.get("PADDLE_KV_HOST_TIER_MB", "0") or 0)
            except ValueError:
                host_tier_mb = 0.0
        self._host_tier_mb = float(host_tier_mb)
        self._host_tier = None              # built in _rebuild_cache
        self._spill_pending = collections.deque()
        # chain-tail digest -> first sampled token: greedy decoding is
        # deterministic over identical params, so a memoized first
        # token makes the no-prefill fault-back admission token-exact
        self._first_tok_memo = collections.OrderedDict()
        self._g_host_tier = metrics.gauge("serving.host_tier_bytes")
        self._h_reclaim_age = metrics.histogram(
            "serving.reclaim_hit_age_s")
        # prefill/decode disaggregation (ISSUE 15): kv_handoff=True
        # primes the page extract/inject executables at warmup — a
        # prefill-role replica finishes prefill-only requests with
        # their prompt pages extracted (submit a Request whose
        # ``prefill_only`` is set), a decode-role replica admits
        # shipped pages via :meth:`submit_prefilled`
        self._handoff = bool(kv_handoff)
        self._extract_jit = None
        self._inject_jit = None
        self._extract_site = _cc.site("serving.extract", maxsize=2)
        self._inject_site = _cc.site("serving.inject", maxsize=2)
        self._inject_queue = collections.deque()
        if self._page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None (compute dtype) or 'int8', got "
                f"{kv_dtype!r} — float overrides go through cache_dtype")
        if kv_dtype == "int8" and kw.get("cache_dtype") is not None:
            raise ValueError(
                "kv_dtype='int8' and cache_dtype are mutually exclusive "
                "— the int8 pool's storage dtype is fixed (int8 pages + "
                "fp32 scales); drop cache_dtype")
        self._kv_quant = kv_dtype == "int8"
        self._kv_saved_counted = False
        self._num_pages_cfg = None if num_pages is None else int(num_pages)
        self._prefix_cache_on = bool(prefix_cache)
        self._prefill_chunk = None          # set after buckets are known
        self._chunk_jobs = collections.deque()
        self._chunk_slots = set()
        self._copy_jit = None
        self._chunk_jit = None
        self._copy_site = _cc.site("serving.copy", maxsize=2)
        self._chunk_site = _cc.site("serving.chunk", maxsize=2)
        self._admit_seq = 0
        super().__init__(model, **kw)
        self._kv_dtype = kv_dtype
        if getattr(self, "_kv_saved_pending", None):
            self._inc("kv_quant_bytes_saved", self._kv_saved_pending)
            self._kv_saved_pending = 0
        ps = self._page_size
        # the gathered page view is maxP*ps == max_len wide, so paged
        # attention sees exactly the slot engine's mask width — that, and
        # identical fallback math, is what keeps parity token-exact
        self.seq_buckets = tuple(sorted(
            {min(-(-b // ps) * ps, self.max_len) for b in self.seq_buckets}))
        if prefill_chunk is not None:
            c = -(-int(prefill_chunk) // ps) * ps
            if c > self.seq_buckets[-1]:
                raise ValueError(
                    f"prefill_chunk {c} exceeds the largest prefill "
                    f"bucket {self.seq_buckets[-1]} — prompts between "
                    "them would be unserveable")
            if self.max_len % c:
                raise ValueError(
                    f"prefill_chunk {c} must divide max_len "
                    f"{self.max_len} (a clamped chunk write would "
                    "corrupt earlier positions)")
            self._prefill_chunk = c
        if self._pp > 1:
            # the 1F1B stage step (models/gpt_pp.py) runs explicit
            # collectives over full-precision dense weights and
            # whole-bucket prefill waves; name each missing composition
            # instead of producing silently-wrong numerics
            if self.quant is not None:
                raise ValueError(
                    "pp > 1 does not compose with quant= yet — the "
                    "stage step has no dequant-matmul path for "
                    "{'qw','scale'} leaves (tp x quant works: "
                    "ServingEngine(tp=N, quant=...))")
            if self._kv_quant:
                raise ValueError(
                    "pp > 1 does not compose with kv_dtype='int8' yet "
                    "— the stage-local pools store the compute dtype")
            if self._prefill_chunk is not None:
                raise ValueError(
                    "pp > 1 prefills whole buckets through the stage "
                    "ring — drop prefill_chunk")
            from ..models import gpt_pp
            gpt_pp.check_pp_config(self.cfg, self._pp)
            # decode microbatching: slots split into pp groups when they
            # divide evenly (keeps every stage busy outside the bubble);
            # otherwise one group — correct, just bubble-bound
            self._pp_microbatch = (self._pp if self.slots % self._pp == 0
                                   else 1)

    # ------------------------------------------------------------ plumbing
    def _aot_sig(self):
        return (f"{super()._aot_sig()}/ps={self._page_size}"
                f"/pages={self._num_pages}/chunk={self._prefill_chunk}")

    def _rebuild_cache(self):
        ps = self._page_size
        if self.max_len % ps:
            raise ValueError(
                f"max_len {self.max_len} must be a multiple of "
                f"page_size {ps}")
        self._pages_per_slot = self.max_len // ps
        num_pages = (self._num_pages_cfg
                     if self._num_pages_cfg is not None
                     else self.slots * self._pages_per_slot + 1)
        self._num_pages = int(num_pages)
        # the prefix hashes are salted with the numeric contract so an
        # int8 pool's pages can never alias an fp pool's (satellite:
        # mixed-fleet prefix keys must not collide across contracts)
        self._pager = self._KVPager(
            self._num_pages, ps, self.slots,
            prefix_cache=self._prefix_cache_on,
            hash_key=f"quant={self.quant or 'none'}"
                     f"/kv={'int8' if self._kv_quant else 'fp'}")
        # host-tier spill capture rides the pager's eviction hook; the
        # tier itself SURVIVES rebuilds (its entries are content-
        # addressed host bytes, valid independent of device state)
        self._pager.evict_hook = self._on_page_evicted
        # captures still pending against the OLD pool are untrusted
        # after a rebuild (the failed dispatch may have consumed it)
        self._spill_pending.clear()
        if self._host_tier is None and self._host_tier_mb > 0 \
                and self._prefix_cache_on:
            self._host_tier = _HostKVTier(
                int(self._host_tier_mb * (1 << 20)),
                hash_key=self._pager.hash_key)
        if self._kv_quant:
            cache = gpt.init_paged_cache_quant(self.cfg, self._num_pages,
                                               ps, mesh=self._mesh)
            self._cache_ks = cache["k_scale"]
            self._cache_vs = cache["v_scale"]
            if not self._kv_saved_counted:
                # bytes the int8+scale pool saves vs the SAME pool at
                # the compute dtype (what a rebuild without kv_dtype
                # would have allocated) — counted once, not per rebuild.
                # The first build happens inside the base constructor
                # before the counters exist; park it for __init__'s tail.
                fp_bytes = 2 * (cache["k"].size
                                * self._jnp.dtype(self._cache_dtype
                                                  or self.cfg.dtype).itemsize)
                q_bytes = sum(int(cache[n].nbytes) for n in
                              ("k", "v", "k_scale", "v_scale"))
                self._kv_saved_pending = max(0, fp_bytes - q_bytes)
                self._kv_saved_counted = True
        else:
            cache = gpt.init_paged_cache(self.cfg, self._num_pages, ps,
                                         dtype=self._cache_dtype,
                                         mesh=self._mesh)
            self._cache_ks = self._cache_vs = None
        self._cache_k, self._cache_v = cache["k"], cache["v"]
        self._tables_np = np.zeros((self.slots, self._pages_per_slot),
                                   np.int32)
        self._chunk_jobs.clear()
        self._chunk_slots.clear()

    def _cache_operands(self):
        """The donated KV pool arrays in executable-operand order:
        (k, v) for the fp pool, (k, k_scale, v, v_scale) for int8."""
        if self._kv_quant:
            return (self._cache_k, self._cache_ks,
                    self._cache_v, self._cache_vs)
        return (self._cache_k, self._cache_v)

    def _set_cache(self, arrs):
        if self._kv_quant:
            (self._cache_k, self._cache_ks,
             self._cache_v, self._cache_vs) = arrs
        else:
            self._cache_k, self._cache_v = arrs

    @property
    def _n_cache(self):
        return 4 if self._kv_quant else 2

    def _chunk_eligible(self, req):
        return (self._prefill_chunk is not None
                and len(req.prompt) > self._prefill_chunk)

    def _check_prompt(self, req):
        need = len(req.prompt) + req.max_new_tokens
        need_pages = self._pager.pages_for(need)
        if need_pages > self._num_pages - 1:
            raise ValueError(
                f"request needs {need_pages} KV pages but the pool only "
                f"has {self._num_pages - 1} — raise num_pages or shrink "
                "the request")
        if self._chunk_eligible(req):
            return                  # the chunked path ignores the ladder
        super()._check_prompt(req)

    def _free_slots(self):
        return [i for i in range(self.slots)
                if not self._active[i] and i not in self._chunk_slots]

    def _queued_total(self):
        return len(self._queue) + len(self._inject_queue)

    def _busy(self):
        return (super()._busy() or bool(self._chunk_jobs)
                or bool(self._inject_queue))

    def _next_admit_seq(self):
        self._admit_seq += 1
        return self._admit_seq

    # ----------------------------------------------------------- admission
    def _admit(self):
        """Wave admission into pages: same same-seq-bucket grouping as
        the slot engine, but each admitted prompt first acquires its
        page table (prefix-cache hits share physical pages).  Page
        exhaustion stops the wave — queued requests simply wait for
        decodes to free pages.  Long prompts divert to the chunked
        path."""
        self._intake_injected()
        self._try_fault_back()
        self._intake_chunked()
        while self._queue and self._free_slots():
            if self._chunk_eligible(self._queue[0]):
                break               # FIFO: the long head waits for intake
            free = self._free_slots()
            group, tables, sbucket, hits_total = [], [], None, 0
            exhausted = False
            while (self._queue and len(group) < len(free)
                   and len(group) < self.batch_buckets[-1]):
                nxt = self._queue[0]
                if self._chunk_eligible(nxt):
                    break
                nxt_b = self._seq_bucket(len(nxt.prompt))
                if sbucket is None:
                    sbucket = nxt_b
                elif nxt_b != sbucket:
                    break           # next wave picks it up
                slot = free[len(group)]
                try:
                    table, hits = self._pager.admit(slot, nxt.prompt)
                except self._PagesExhausted:
                    exhausted = True
                    break
                self._queue.popleft()
                nxt.slot = slot
                group.append(nxt)
                tables.append(table)
                hits_total += hits
            if not group:
                break
            self._prefill_group(group, tables, sbucket, hits_total)
            if exhausted:
                break
        self._g_queue.set(self._queued_total())
        occ = int(self._active.sum())
        self._g_occ.set(occ)
        if not self._warming:
            self._occ_peak = max(self._occ_peak, occ)
            if occ > self._g_occ_peak.value:
                self._g_occ_peak.set(occ)

    def _prefill_group(self, group, tables, sbucket, hits):
        jnp = self._jnp
        ps = self._page_size
        bbucket = self._batch_bucket(len(group))
        maxPb = sbucket // ps
        toks = np.zeros((bbucket, sbucket), np.int32)
        lens = np.ones((bbucket,), np.int32)    # pad rows: len 1
        ptab = np.zeros((bbucket, maxPb), np.int32)   # pads -> scratch
        for r, req in enumerate(group):
            toks[r, :len(req.prompt)] = req.prompt
            lens[r] = len(req.prompt)
            ptab[r, :len(tables[r])] = tables[r]
        fresh = sum(len(t) for t in tables) - hits
        self._inc("prefix_page_hits", hits)
        self._inc("prefix_page_misses", fresh)
        # visible to _abort_inflight, same contract as the base engine
        self._admitting = group
        if tracing.enabled() and not self._warming:
            for req in group:
                tracing.event(
                    "queue_wait", trace_id=req.trace_id,
                    request_id=req.id, batch=bbucket, seq=sbucket,
                    wait_s=round(
                        time.perf_counter() - req.submit_t, 6))
        donate = self._donate()
        operands = (self.params, *self._cache_operands(),
                    jnp.asarray(toks), jnp.asarray(lens),
                    jnp.asarray(ptab))
        fn = self._prefill.get(
            _cc.make_key(bbucket, sbucket, donate=donate,
                         mesh=self._mesh_key()),
            lambda: self._build_prefill(bbucket, sbucket),
            stable_key=self._aot_key("prefill", b=bbucket, s=sbucket),
            example_args=operands, topology=self._topology())
        t0 = time.perf_counter()
        with timeline.span("serving.prefill", batch=bbucket, seq=sbucket,
                           paged=True):
            out = fn(*operands)
        self._set_cache(out[:self._n_cache])
        first_tok = out[self._n_cache]
        # ptl: disable-next=PTL004 -- capture_logits debug mode readback
        logits_np = (np.asarray(out[self._n_cache + 1])
                     if self.capture_logits else None)
        self._inc("prefill_calls")
        self._count_quant_matmuls()
        # sampled-first-token readback: the one designed sync point of
        # the paged prefill wave
        # ptl: disable-next=PTL004 -- sampled-first-token readback
        first_np = np.asarray(first_tok)
        for r, req in enumerate(group):
            s = req.slot
            self._tables_np[s] = 0
            self._tables_np[s, :len(tables[r])] = tables[r]
            self._lens[s] = len(req.prompt)
            self._active[s] = True
            self._slot_req[s] = req
            req._admit_seq = self._next_admit_seq()
            self._append_token(req, int(first_np[r]),
                               logits_np[r] if logits_np is not None
                               else None)
            self._last_tok[s] = int(first_np[r])
            self._inc("requests_admitted")
            self._memo_first_token(req)
            if _faults.active() and not self._warming:
                _faults.replica_kill_check(
                    request=self._counts["requests_admitted"])
            self._maybe_finish_prefill_only(req)
        self._admitting = []
        if not self._warming:
            self._h_prefill.observe(time.perf_counter() - t0)

    def _build_prefill(self, b, s):
        """Paged prefill executable: causal forward over the padded
        prompts, then one batched scatter of the filled K/V page chunks
        into the DONATED pool through the page tables (pad rows target
        the scratch page; shared pages receive content identical to
        what they already hold, so duplicate indices are benign).

        With ``kv_dtype="int8"`` the forward still runs — and attends
        its own prompt — in the compute dtype; the K/V QUANTIZE ON
        WRITE (per-position-per-head absmax, models/gpt.py::quantize_kv)
        as they scatter into the int8 pool, scales landing in the scale
        arrays at the same page coordinates.  Quantization error only
        ever enters on later reads."""
        jax, jnp = self._jax, self._jnp
        cfg = self.cfg
        ps = self._page_size
        pr = s // ps
        cap = self.capture_logits
        kvq = self._kv_quant

        if self._pp > 1:
            # stage-partitioned wave: one shard_map over the ('pp','tp')
            # mesh runs the 1F1B fill, each stage scattering its OWN
            # layer range's pages (models/gpt_pp.py).  Same operand
            # order and outputs as the GSPMD path below.
            from ..models import gpt_pp
            pre = gpt_pp.make_prefill_step(
                cfg, self._mesh, self._param_specs, s=s, b=b,
                page_size=ps)

            def prefill_pp(params, cache_k, cache_v, tokens, lens, ptab):
                ck, cv, first_tok, last = pre(
                    params, cache_k, cache_v, tokens, lens, ptab)
                out_cache = self._constrain_cache((ck, cv))
                if cap:
                    return (*out_cache, first_tok, last)
                return (*out_cache, first_tok)

            donate = ((1, 2) if _donation_enabled() else ())
            return jax.jit(prefill_pp, donate_argnums=donate)

        def prefill(params, *args):
            if kvq:
                cache_k, k_scale, cache_v, v_scale = args[:4]
                tokens, lens, ptab = args[4:]
                fresh = gpt.init_cache(cfg, b, s,
                                       dtype=jnp.dtype(cfg.dtype))
            else:
                cache_k, cache_v = args[:2]
                tokens, lens, ptab = args[2:]
                fresh = gpt.init_cache(cfg, b, s, dtype=cache_k.dtype)
            logits, filled = gpt.forward_cached(params, tokens, cfg, fresh)
            L = cfg.num_layers
            nh, hd = cfg.num_heads, cfg.head_dim
            flat = ptab.reshape(-1)
            fk = filled["k"].reshape(L, b * pr, ps, nh, hd)
            fv = filled["v"].reshape(L, b * pr, ps, nh, hd)
            if kvq:
                fkq, fks = gpt.quantize_kv(fk)
                fvq, fvs = gpt.quantize_kv(fv)
                cache_k = cache_k.at[:, flat].set(fkq)
                k_scale = k_scale.at[:, flat].set(fks)
                cache_v = cache_v.at[:, flat].set(fvq)
                v_scale = v_scale.at[:, flat].set(fvs)
                out_cache = (cache_k, k_scale, cache_v, v_scale)
            else:
                cache_k = cache_k.at[:, flat].set(fk)
                cache_v = cache_v.at[:, flat].set(fv)
                out_cache = (cache_k, cache_v)
            out_cache = self._constrain_cache(out_cache)
            idx = jnp.clip(lens - 1, 0, s - 1)
            last = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1)[:, 0]      # [b, V]
            first_tok = jnp.argmax(last, -1).astype(jnp.int32)
            if cap:
                return (*out_cache, first_tok, last)
            return (*out_cache, first_tok)

        n = self._n_cache
        donate = tuple(range(1, 1 + n)) if _donation_enabled() else ()
        return self._jax.jit(prefill, donate_argnums=donate)

    # ------------------------------------------------------ chunked prefill
    def _intake_chunked(self):
        """Claim a slot + the full prompt's page table for long prompts
        at the queue head; the chunks themselves run one per engine
        iteration in :meth:`_advance_chunks`.  Fresh pages are NOT
        prefix-registered until their content lands (deferred
        registration) — a concurrent identical prompt must never share
        an unwritten page."""
        if self._prefill_chunk is None:
            return
        while self._queue and self._chunk_eligible(self._queue[0]):
            free = self._free_slots()
            if not free:
                return
            req = self._queue[0]
            slot = free[0]
            try:
                table, hits = self._pager.admit(slot, req.prompt,
                                                defer_register=True)
            except self._PagesExhausted:
                return              # decodes will free pages; retry later
            self._queue.popleft()
            req.slot = slot
            req._chunk_pos = 0
            req._chunk_time = 0.0
            req._admit_seq = self._next_admit_seq()
            self._chunk_slots.add(slot)
            self._chunk_jobs.append(req)
            self._slot_req[slot] = req
            self._tables_np[slot] = 0
            self._tables_np[slot, :len(table)] = table
            self._inc("prefix_page_hits", hits)
            self._inc("prefix_page_misses", len(table) - hits)

    def _advance_chunks(self):
        """Run ONE prefill chunk of the oldest chunked admission — the
        interleaving contract: in-flight decodes get an iteration
        between every pair of chunks instead of stalling behind the
        whole long prompt."""
        if not self._chunk_jobs:
            return
        jnp = self._jnp
        req = self._chunk_jobs[0]
        C = self._prefill_chunk
        n = len(req.prompt)
        pos = req._chunk_pos
        take = min(C, n - pos)
        toks = np.zeros((1, C), np.int32)
        toks[0, :take] = req.prompt[pos:pos + take]
        s = req.slot
        operands = (self.params, *self._cache_operands(),
                    jnp.asarray(toks), jnp.asarray(self._tables_np[s]),
                    np.int32(pos), np.int32(take))
        if self._chunk_jit is None:
            donate = self._donate()
            self._chunk_jit = self._chunk_site.get(
                _cc.make_key("chunk", C, donate=donate,
                             mesh=self._mesh_key()),
                lambda: self._build_chunk(C),
                stable_key=self._aot_key("chunk", c=C),
                example_args=operands, topology=self._topology())
            self._inc("prefill_compiles")
        t0 = time.perf_counter()
        with timeline.span("serving.prefill_chunk", pos=pos, take=take):
            out = self._chunk_jit(*operands)
        self._set_cache(out[:self._n_cache])
        tok = out[self._n_cache]
        # ptl: disable-next=PTL004 -- capture_logits debug mode readback
        row_np = (np.asarray(out[self._n_cache + 1])
                  if self.capture_logits else None)
        self._inc("prefill_chunks")
        self._count_quant_matmuls()
        if tracing.enabled() and not self._warming:
            tracing.event("prefill_chunk", trace_id=req.trace_id,
                          request_id=req.id, pos=pos, take=take,
                          chunk_s=round(time.perf_counter() - t0, 6),
                          engine=self._engine_id)
        req._chunk_pos = pos + take
        # the prefill histogram records the WHOLE admission's work, so
        # accumulate per-chunk durations and observe once at the end
        req._chunk_time += time.perf_counter() - t0
        self._pager.register_prompt(s, req._chunk_pos)
        if req._chunk_pos < n:
            return                  # decode runs before the next chunk
        # final chunk: the prompt is in — the sampled token admits the
        # request into the decode pool like a one-shot prefill would
        self._chunk_jobs.popleft()
        self._chunk_slots.discard(s)
        self._lens[s] = n
        self._active[s] = True
        self._append_token(req, int(tok), row_np)
        self._last_tok[s] = int(tok)
        self._inc("requests_admitted")
        self._memo_first_token(req)
        if not self._warming:
            self._h_prefill.observe(req._chunk_time)
        if _faults.active() and not self._warming:
            _faults.replica_kill_check(
                request=self._counts["requests_admitted"])
        self._maybe_finish_prefill_only(req)

    def _build_chunk(self, C):
        """ONE executable serves every chunk of every long prompt: the
        absolute position offset and the chunk's true token count are
        traced scalars, so chunk index never changes the signature.
        int8 pools route through ``gpt.forward_paged_chunk_quant``
        (dequantized gather view in, quantized chunk-only scatter
        out)."""
        jax, jnp = self._jax, self._jnp
        cfg = self.cfg
        cap = self.capture_logits
        kvq = self._kv_quant

        def chunk(params, *args):
            if kvq:
                cache, (toks, ptab_row, offset, tlen) = args[:4], args[4:]
                logits, *cache = gpt.forward_paged_chunk_quant(
                    params, toks, cfg, *cache, ptab_row, offset)
            else:
                cache, (toks, ptab_row, offset, tlen) = args[:2], args[2:]
                logits, *cache = gpt.forward_paged_chunk(
                    params, toks, cfg, *cache, ptab_row, offset)
            last = jax.lax.dynamic_index_in_dim(logits[0], tlen - 1, 0,
                                                keepdims=False)    # [V]
            tok = jnp.argmax(last, -1).astype(jnp.int32)
            cache = self._constrain_cache(cache)
            if cap:
                return (*cache, tok, last)
            return (*cache, tok)

        donate = (tuple(range(1, 1 + self._n_cache))
                  if _donation_enabled() else ())
        return jax.jit(chunk, donate_argnums=donate)

    # ----------------------------------------------------- page lifecycle
    def _finish(self, req, reason):
        s = req.slot
        super()._finish(req, reason)
        if s is not None:
            self._pager.release(s)
            self._tables_np[s] = 0

    def _get_copy_jit(self):
        if self._copy_jit is None:
            self._copy_jit = self._copy_site.get(
                _cc.make_key("copy", donate=self._donate(0),
                             mesh=self._mesh_key()),
                self._build_copy,
                stable_key=self._aot_key("copy"),
                example_args=(*self._cache_operands(),
                              np.int32(0), np.int32(0)),
                topology=self._topology())
        return self._copy_jit

    def _copy_page(self, src, dst):
        """Device-side copy-on-write: duplicate page ``src`` into the
        freshly-owned ``dst`` before the diverging write lands.  One
        jitted donated executable, compiled once (warmup primes it).
        On the int8 pool the page's scale rows travel WITH its bytes —
        an int8 page without its scales is garbage."""
        self._set_cache(self._get_copy_jit()(
            *self._cache_operands(), np.int32(src), np.int32(dst)))
        self._inc("cow_copies")

    def _build_copy(self):
        jax = self._jax

        def cp(*args):
            arrs, (src, dst) = args[:-2], args[-2:]
            return self._constrain_cache(
                tuple(a.at[:, dst].set(a[:, src]) for a in arrs))

        donate = (tuple(range(self._n_cache))
                  if _donation_enabled() else ())
        return jax.jit(cp, donate_argnums=donate)

    # ------------------------------------------- KV handoff (ISSUE 15)
    #
    # Prefill/decode disaggregation ships a finished prompt's KV pages
    # from a prefill-role engine to a decode-role engine (DistServe/
    # Splitwise): the prefill engine admits a ``prefill_only`` request
    # through the NORMAL wave/chunked paths, then — instead of decoding
    # — extracts its pages to host bytes, finishes it with reason
    # "prefill_done", and releases the pages (prompt pages retire to
    # the prefix-reclaim LRU, so a repeated system prompt prefills
    # free).  The decode engine re-acquires a page table for the SAME
    # prompt (prefix hits share physical pages — the shipped bytes are
    # deterministic, so rewriting a shared page writes what it already
    # holds) and scatters the payload in with ONE injection executable.
    # Both directions are one fixed-shape executable each (pages padded
    # to the per-slot table width), so the zero-steady-state-compiles
    # invariant survives disaggregation.

    def _build_extract(self):
        jax = self._jax
        n = self._n_cache

        def extract(*args):
            cache, pages = args[:n], args[-1]
            return tuple(c[:, pages] for c in cache)

        return jax.jit(extract)     # read-only: the pool is NOT donated

    def _extract_pages_row(self, pages_row):
        """Dispatch the fixed-width page-gather executable over an
        explicit full-width ``pages_row`` (pads aimed at scratch) and
        return the still-on-device output arrays — the shared primitive
        under the disaggregation handoff AND the host-tier spill
        capture, so both ride ONE executable and neither ever compiles
        in steady state."""
        jnp = self._jnp
        operands = (*self._cache_operands(), jnp.asarray(pages_row))
        if self._extract_jit is None:
            self._extract_jit = self._extract_site.get(
                _cc.make_key("extract", mesh=self._mesh_key()),
                self._build_extract,
                stable_key=self._aot_key("extract"),
                example_args=operands, topology=self._topology())
            self._inc("handoff_compiles")
        return self._extract_jit(*operands)

    def _extract_slot_kv(self, slot, n_pages):
        """The slot's first ``n_pages`` pages of every pool operand as
        host arrays (k, v — plus scales on the int8 pool), via one
        fixed-width gather executable."""
        with timeline.span("serving.kv_extract", pages=int(n_pages)):
            out = self._extract_pages_row(self._tables_np[slot])
        self._inc("kv_extracts")
        # the handoff readback: these pages LEAVE the replica as wire
        # bytes by design — the disaggregation shipping path, not a
        # hot-loop leak
        # ptl: disable-next=PTL004 -- KV handoff readback (pages ship out)
        return [np.asarray(a)[:, :int(n_pages)] for a in out]

    def _maybe_finish_prefill_only(self, req):
        """Finish a ``prefill_only`` admission the moment its prompt is
        in: pages extracted onto ``req.kv_payload``, request finished
        with reason "prefill_done" (slot + pages released).  A request
        that finished NATURALLY during admission (eos on the first
        token, max_new_tokens == 1) ships no pages — its completion is
        already final."""
        if not req.prefill_only or req.done or self._warming:
            return
        s = req.slot
        n_pages = len(self._pager.tables[s])
        req.kv_payload = self._extract_slot_kv(s, n_pages)
        kv_bytes = sum(int(a.nbytes) for a in req.kv_payload)
        self._inc("kv_handoff_bytes", kv_bytes)
        if tracing.enabled():
            tracing.event("extract", trace_id=req.trace_id,
                          request_id=req.id, pages=n_pages,
                          kv_bytes=kv_bytes, engine=self._engine_id)
        self._finish(req, "prefill_done")

    def submit_prefilled(self, req, first_token, kv_arrays):
        """Admit a request whose prompt KV was prefilled on ANOTHER
        engine (the disaggregation handoff).  ``req`` is a prepared
        :class:`Request`; ``kv_arrays`` is one host array per pool
        operand, shaped ``[L, n_pages, page_size, ...]`` for the
        prompt's pages (what the prefill side's ``kv_payload`` holds);
        ``first_token`` is the prefill's sampled first token.  Queued
        on the injection queue — the next :meth:`step` acquires pages
        and scatters the payload in.  Identical params make the decode
        byte-stream token-exact with a never-disaggregated run."""
        if not isinstance(req, Request):
            raise TypeError("submit_prefilled wants a prepared Request")
        if not self._handoff:
            # symmetric with submit()'s prefill_only guard: without
            # kv_handoff=True the inject executable was never primed,
            # so the first injection would compile in live traffic
            raise ValueError(
                "handed-off admission needs "
                "PagedServingEngine(kv_handoff=True) — this engine's "
                "warmup never primed the injection executable")
        if self.capture_logits:
            raise ValueError(
                "capture_logits engines cannot admit handed-off "
                "requests — the first token's logits row stayed on the "
                "prefill replica, so the per-token capture would be "
                "misaligned from its first entry")
        need_pos = len(req.prompt) + req.max_new_tokens
        if need_pos > self.max_len:
            # same admission bound as submit(): past max_len the
            # fixed-width page table overflows and positions reuse the
            # last positional embedding — reject up front, not mid-step
            raise ValueError(
                f"request needs {need_pos} cache positions "
                f"(prompt {len(req.prompt)} + {req.max_new_tokens} new) "
                f"> max_len {self.max_len}")
        need = self._pager.pages_for(need_pos)
        if need > self._num_pages - 1:
            raise ValueError(
                f"request needs {need} KV pages but the pool only has "
                f"{self._num_pages - 1}")
        ops = self._cache_operands()
        if len(kv_arrays) != len(ops):
            raise ValueError(
                f"kv payload has {len(kv_arrays)} arrays; this pool "
                f"has {len(ops)} operands (fp: k,v; int8: k,k_scale,"
                "v,v_scale)")
        n_pages = self._pager.pages_for(len(req.prompt))
        arrays = []
        for a, pool in zip(kv_arrays, ops):
            a = np.asarray(a)
            want = (pool.shape[0], n_pages) + tuple(pool.shape[2:])
            if tuple(a.shape) != want or a.dtype != np.dtype(pool.dtype):
                raise ValueError(
                    f"kv payload shape/dtype {a.shape}/{a.dtype} does "
                    f"not match the pool's page layout {want}/"
                    f"{pool.dtype} — mismatched engine configs can "
                    "never hand off")
            arrays.append(a)
        if self._queued_total() >= self.max_queue:
            self._inc("queue_rejects")
            raise ServingQueueFull(
                f"queue depth {self._queued_total()} at max_queue "
                f"{self.max_queue}")
        req._inject = arrays
        req._inject_tok = int(first_token)
        self._inject_queue.append(req)
        self._g_queue.set(self._queued_total())
        return req

    def _build_inject(self):
        jax = self._jax
        n = self._n_cache

        def inject(*args):
            cache, payload, pages = args[:n], args[n:2 * n], args[-1]
            return self._constrain_cache(tuple(
                c.at[:, pages].set(p) for c, p in zip(cache, payload)))

        donate = (tuple(range(n)) if _donation_enabled() else ())
        return jax.jit(inject, donate_argnums=donate)

    def _inject_call(self, pages_row, payload):
        """One injection dispatch: scatter ``payload`` (already padded
        to the table width, pad rows aimed at the scratch page) into
        the donated pool at ``pages_row``."""
        jnp = self._jnp
        operands = (*self._cache_operands(),
                    *(jnp.asarray(p) for p in payload),
                    jnp.asarray(pages_row))
        if self._inject_jit is None:
            self._inject_jit = self._inject_site.get(
                _cc.make_key("inject", donate=self._donate(0),
                             mesh=self._mesh_key()),
                self._build_inject,
                stable_key=self._aot_key("inject"),
                example_args=operands, topology=self._topology())
            self._inc("handoff_compiles")
        with timeline.span("serving.kv_inject"):
            self._set_cache(self._inject_jit(*operands))

    def _pad_payload(self, arrays, n_pages):
        maxP = self._pages_per_slot
        out = []
        for a in arrays:
            pad = np.zeros((a.shape[0], maxP) + tuple(a.shape[2:]),
                           a.dtype)
            pad[:, :n_pages] = a
            out.append(pad)
        return out

    def _intake_injected(self):
        """Admit shipped-KV requests from the injection queue: acquire
        a page table for the prompt (prefix hits share pages — the
        injection rewrites bytes identical to what a shared page
        already holds), scatter the payload in, and activate the slot
        with the prefill's first token already committed.  Page
        exhaustion leaves the queue intact — decodes free pages."""
        while self._inject_queue:
            free = self._free_slots()
            if not free:
                return
            req = self._inject_queue[0]
            slot = free[0]
            try:
                table, hits = self._pager.admit(slot, req.prompt)
            except self._PagesExhausted:
                return
            self._inject_queue.popleft()
            req.slot = slot
            n_pages = len(table)
            pages_row = np.zeros((self._pages_per_slot,), np.int32)
            pages_row[:n_pages] = table
            self._inject_call(pages_row,
                              self._pad_payload(req._inject, n_pages))
            self._inc("prefix_page_hits", hits)
            self._inc("prefix_page_misses", n_pages - hits)
            self._inc("kv_injects")
            if tracing.enabled() and not self._warming:
                tracing.event("inject", trace_id=req.trace_id,
                              request_id=req.id, pages=n_pages,
                              prefix_hits=hits, engine=self._engine_id)
            self._tables_np[slot] = pages_row
            self._lens[slot] = len(req.prompt)
            self._active[slot] = True
            self._slot_req[slot] = req
            req._admit_seq = self._next_admit_seq()
            self._append_token(req, req._inject_tok, None)
            self._last_tok[slot] = req._inject_tok
            self._inc("requests_admitted")
            self._g_queue.set(self._queued_total())
            self._memo_first_token(req)
            if _faults.active() and not self._warming:
                _faults.replica_kill_check(
                    request=self._counts["requests_admitted"])

    # ------------------------------------------- host page tier (ISSUE 17)
    #
    # The tier turns device evictions into demotions: the pager's
    # reclaim-LRU eviction hook captures the page's bytes through the
    # SAME fixed-width extract executable the disaggregation handoff
    # uses (one synthetic row, the pid at position 0), and the post-step
    # drain moves them to the pinned-host LRU with a content-hash stamp.
    # A later prompt whose page chain is fully covered by device hits
    # plus hash-verified host entries — and whose first token is
    # memoized (greedy decoding is deterministic, so the first token is
    # a pure function of params and prompt) — admits through the
    # donated inject executable WITHOUT re-prefilling.  Every moving
    # part reuses an already-warm executable, so the zero-steady-state-
    # compiles invariant survives the tier.

    def _on_page_evicted(self, pid, key):
        """Pager eviction hook: dispatch the page-gather NOW, while the
        pid's bytes are still valid (the caller reuses the pid right
        after), but keep the result on device — the host readback
        defers to the post-step drain so a slow host copy
        (``spill_stall``) never blocks the decode dispatch."""
        if self._warming or self._host_tier is None:
            return
        row = np.zeros((self._pages_per_slot,), np.int32)  # pads->scratch
        row[0] = pid
        self._spill_pending.append((key, self._extract_pages_row(row)))

    def _drain_spills(self):
        """Deferred half of the spill: host readback, content-hash
        stamp, LRU insert.  Runs from ``step()``'s finally — strictly
        after the decode dispatch of the step that evicted."""
        if not self._spill_pending or self._host_tier is None:
            self._spill_pending.clear()
            return
        while self._spill_pending:
            key, arrays = self._spill_pending.popleft()
            if _faults.active() and not self._warming:
                stall = _faults.spill_stall()
                if stall is not None:
                    time.sleep(stall)
            # the page moves DOWN a tier by design — a demotion copy,
            # not a hot-loop leak
            # ptl: disable-next=PTL004 -- host-tier spill readback
            host = [np.asarray(a)[:, :1].copy() for a in arrays]
            self._host_tier.put(key, host)
            if (_faults.active() and not self._warming
                    and _faults.host_tier_corrupt()):
                self._host_tier.corrupt(key)
            self._inc("pages_spilled")
            self._inc("spill_bytes", sum(int(h.nbytes) for h in host))
        self._g_host_tier.set(self._host_tier.bytes)

    def step(self):
        """Base step plus the spill drain.  The drain lives HERE (not
        ``_step_inner``, which the speculative engine overrides
        wholesale) so every paged variant demotes evicted pages."""
        try:
            return super().step()
        finally:
            if self._spill_pending:
                self._drain_spills()

    def _memo_first_token(self, req):
        """Record the prompt's greedy first token under its chain-tail
        page key (already salted by quant/kv-dtype config) — the
        admission ticket for a later no-prefill fault-back."""
        if self._host_tier is None or self._warming or not req.tokens:
            return
        keys = self._pager._prompt_keys(req.prompt)
        if not keys:
            return
        memo = self._first_tok_memo
        memo[keys[-1]] = int(req.tokens[0])
        memo.move_to_end(keys[-1])
        while len(memo) > 8192:
            memo.popitem(last=False)

    def _try_fault_back(self):
        """Head-of-queue fault-back admission: when the head prompt's
        FULL page chain is covered by device prefix hits plus
        hash-verified host-tier entries, and its first token is
        memoized, admit through the inject executable instead of
        re-prefilling.  Anything short of full verified coverage falls
        through to the normal prefill paths (head-only keeps FIFO
        order; a corrupt host entry is dropped and the prompt simply
        re-prefills — bad KV is never served)."""
        if (self._host_tier is None or not self._prefix_cache_on
                or self.capture_logits):
            return
        while self._queue:
            free = self._free_slots()
            if not free:
                return
            req = self._queue[0]
            keys = self._pager._prompt_keys(req.prompt)
            if not keys or keys[-1] not in self._first_tok_memo:
                return
            fetched = {}
            covered = True
            for key in keys:
                if self._pager.cached_page(key) is not None:
                    continue
                got = self._host_tier.fetch(key)
                if got == "corrupt":
                    self._inc("fault_back_rejects")
                    covered = False
                    break
                if got is None:
                    covered = False
                    break
                fetched[key] = got
            if not covered or not fetched:
                return      # device-only hits: the prefill wave wins
            slot = free[0]
            try:
                table, hit_flags = self._pager.admit_pinned(
                    slot, req.prompt)
            except self._PagesExhausted:
                return
            # inject ONLY the missing pages (device hits already hold
            # their bytes); fresh pids pack the row head, pads scratch
            miss = [(i, k) for i, (k, h)
                    in enumerate(zip(keys, hit_flags)) if not h]
            pages_row = np.zeros((self._pages_per_slot,), np.int32)
            cols = None
            for j, (i, key) in enumerate(miss):
                pages_row[j] = table[i]
                arrays, age = fetched[key]
                self._h_reclaim_age.observe(age)
                if cols is None:
                    cols = [[a] for a in arrays]
                else:
                    for lst, a in zip(cols, arrays):
                        lst.append(a)
            payload = [np.concatenate(lst, axis=1) for lst in cols]
            self._inject_call(pages_row,
                              self._pad_payload(payload, len(miss)))
            self._queue.popleft()
            req.slot = slot
            n_pages = len(table)
            self._tables_np[slot] = 0
            self._tables_np[slot, :n_pages] = table
            self._lens[slot] = len(req.prompt)
            self._active[slot] = True
            self._slot_req[slot] = req
            req._admit_seq = self._next_admit_seq()
            tok = int(self._first_tok_memo[keys[-1]])
            self._append_token(req, tok, None)
            self._last_tok[slot] = tok
            # the whole chain served without prefill: every page is a
            # prefix hit from the fleet's point of view
            self._inc("prefix_page_hits", n_pages)
            self._inc("pages_faulted_back", len(miss))
            self._inc("fault_backs")
            self._inc("kv_injects")
            self._inc("requests_admitted")
            self._g_queue.set(self._queued_total())
            if not self._warming and timeline.telemetry_dir():
                timeline.emit({"event": "kv_fault_back",
                               "request_id": str(req.id),
                               "pages": len(miss),
                               "device_hits": n_pages - len(miss)})
            if not self._warming:
                tracing.event("fault_back", trace_id=req.trace_id,
                              request_id=req.id, pages=len(miss),
                              device_hits=n_pages - len(miss),
                              engine=self._engine_id)
            if _faults.active() and not self._warming:
                _faults.replica_kill_check(
                    request=self._counts["requests_admitted"])
            self._maybe_finish_prefill_only(req)

    def _newest_victim(self):
        """The most recently admitted in-flight request (decode-active
        or mid-chunked-prefill) — the preemption policy's target."""
        cands = [r for r in self._slot_req if r is not None and not r.done]
        if not cands:
            return None
        return max(cands, key=lambda r: getattr(r, "_admit_seq", -1))

    def _preempt(self, req, why):
        """Page-exhaustion eviction: free the victim's slot and pages,
        scrub it back to its prompt, and put it at the queue head for
        re-admission once pages free up.  NAMED (telemetry event,
        ``preemptions`` counter, ``Request.preemptions``) — exhaustion
        is never a silent stall or loss."""
        s = req.slot
        if s is not None:
            self._pager.release(s)
            self._tables_np[s] = 0
            self._active[s] = False
            self._lens[s] = 0
            self._slot_req[s] = None
            if s in self._chunk_slots:
                self._chunk_slots.discard(s)
                try:
                    self._chunk_jobs.remove(req)
                except ValueError:
                    pass
        req.reset_for_retry()
        req.preemptions += 1
        if req._inject is not None:
            # a preempted INJECTED request re-injects its shipped pages
            # (re-prefilling locally would be correct but would drag
            # prefill work onto a decode-role replica)
            self._inject_queue.appendleft(req)
        else:
            self._queue.appendleft(req)
        self._inc("preemptions")
        self._g_queue.set(self._queued_total())
        if not self._warming and timeline.telemetry_dir():
            timeline.emit({"event": "page_exhaustion",
                           "request_id": str(req.id),
                           "action": "preempted", "reason": why})
        if not self._warming:
            tracing.event("preemption", trace_id=req.trace_id,
                          request_id=req.id, reason=str(why)[:160],
                          preemptions=req.preemptions,
                          engine=self._engine_id)

    def _ensure_decode_pages(self):
        """Give every active slot a writable position for this step's
        token: a fresh tail page on a page boundary, a COW copy when the
        tail is shared.  On exhaustion, preempt the newest request and
        retry (``ensure_append`` is idempotent, so re-walking already-
        ensured slots is safe) — progress is guaranteed because a lone
        request always fits (submit enforces it)."""
        ps = self._page_size
        wpages = np.zeros((self.slots,), np.int32)   # inactive -> scratch
        woffs = np.zeros((self.slots,), np.int32)
        while True:
            try:
                for s in range(self.slots):
                    if not self._active[s]:
                        wpages[s] = 0
                        woffs[s] = 0
                        continue
                    pos = int(self._lens[s])
                    pid, off, cow_src = self._pager.ensure_append(s, pos)
                    if cow_src is not None:
                        self._copy_page(cow_src, pid)
                    self._tables_np[s, pos // ps] = pid
                    wpages[s] = pid
                    woffs[s] = off
                return wpages, woffs
            except self._PagesExhausted as e:
                victim = self._newest_victim()
                if victim is None:
                    raise
                self._preempt(victim, str(e))

    # ------------------------------------------------------------- driving
    def _step_inner(self):
        self._admit()
        self._advance_chunks()
        if not self._active.any():
            return
        jnp = self._jnp
        if _faults.active() and not self._warming:
            if _faults.page_exhaustion_check(
                    step=self._counts["decode_steps"] + 1):
                victim = self._newest_victim()
                if victim is not None:
                    self._preempt(victim, "injected page_exhaustion")
            _faults.engine_step_error(self._counts["decode_steps"] + 1)
            _faults.replica_kill_check(
                step=self._counts["decode_steps"] + 1)
        if not self._active.any():
            return                  # the injected preemption emptied it
        finished = []
        wpages, woffs = self._ensure_decode_pages()
        if not self._active.any():
            return
        operands = (self.params, *self._cache_operands(),
                    jnp.asarray(self._tables_np), jnp.asarray(wpages),
                    jnp.asarray(woffs), jnp.asarray(self._lens),
                    jnp.asarray(self._last_tok))
        if self._decode_jit is None:
            donate = self._donate()
            self._decode_jit = self._decode_site.get(
                _cc.make_key("decode", donate=donate,
                             mesh=self._mesh_key()),
                self._build_decode,
                stable_key=self._aot_key("decode"),
                example_args=operands, topology=self._topology())
            self._inc("decode_compiles")
        t0 = time.perf_counter()
        with timeline.span("serving.decode_step",
                           active=int(self._active.sum()), paged=True):
            out = self._decode_jit(*operands)
        self._set_cache(out[:self._n_cache])
        nxt = out[self._n_cache]
        # ptl: disable-next=PTL004 -- capture_logits debug mode readback
        logits_np = (np.asarray(out[self._n_cache + 1])
                     if self.capture_logits else None)
        self._inc("decode_steps")
        self._count_quant_matmuls()
        # sampled-token readback: THE designed device->host sync of the
        # paged decode loop
        # ptl: disable-next=PTL004 -- sampled-token readback
        nxt_np = np.asarray(nxt)
        for s in range(self.slots):
            if not self._active[s]:
                continue
            req = self._slot_req[s]
            self._lens[s] += 1
            self._append_token(req, int(nxt_np[s]),
                               logits_np[s] if logits_np is not None
                               else None)
            self._last_tok[s] = int(nxt_np[s])
            if req.done:
                finished.append(req)
        dt = time.perf_counter() - t0
        if not self._warming:
            self._h_decode.observe(dt)
        self._g_occ.set(int(self._active.sum()))
        self._update_tps()
        if not self._warming and timeline.telemetry_dir():
            timeline.emit({"event": "serving_step",
                           "active": int(self._active.sum()),
                           "queue": len(self._queue),
                           "decode_s": round(dt, 6),
                           "finished": len(finished),
                           "pages_in_use": self._pager.pages_in_use(),
                           "pages_spilled":
                               self._counts.get("pages_spilled", 0),
                           "pages_faulted_back":
                               self._counts.get("pages_faulted_back", 0),
                           "chain_digests":
                               self._pager.stats()["chain_digest_count"],
                           # per-process total order + emitter (ISSUE 19)
                           "seq": tracing.seq(),
                           "engine": self._engine_id,
                           "replica": self._replica,
                           "finished_ids": [str(r.id) for r in finished]})
        if tracing.enabled() and not self._warming:
            for r in finished:
                tracing.event("decode_iter", trace_id=r.trace_id,
                              request_id=r.id, iters=len(r.tokens),
                              decode_s=round(dt, 6),
                              engine=self._engine_id)

    def _build_decode(self):
        jax, jnp = self._jax, self._jnp
        cfg = self.cfg
        cap = self.capture_logits
        kvq = self._kv_quant

        if self._pp > 1:
            # stage-partitioned decode: the 1F1B microbatch tick loop
            # inside ONE shard_map (models/gpt_pp.py) — page table,
            # write coordinates and lengths stay traced operands, so
            # this is still the one decode executable forever
            from ..models import gpt_pp
            step_pp = gpt_pp.make_decode_step(
                cfg, self._mesh, self._param_specs, self._pp_microbatch)

            def decode_pp(params, cache_k, cache_v, page_table, wpages,
                          woffs, lens, toks):
                logits, ck, cv = step_pp(params, toks, cache_k, cache_v,
                                         page_table, wpages, woffs, lens)
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                cache = self._constrain_cache((ck, cv))
                if cap:
                    return (*cache, nxt, logits)
                return (*cache, nxt)

            donate = ((1, 2) if _donation_enabled() else ())
            return jax.jit(decode_pp, donate_argnums=donate)

        def decode(params, *args):
            n = 4 if kvq else 2
            cache = args[:n]
            page_table, wpages, woffs, lens, toks = args[n:]
            step = (gpt.decode_step_paged_quant if kvq
                    else gpt.decode_step_paged)
            logits, *cache = step(params, toks, cfg, *cache, page_table,
                                  wpages, woffs, lens)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            cache = self._constrain_cache(cache)
            if cap:
                return (*cache, nxt, logits)
            return (*cache, nxt)

        donate = (tuple(range(1, 1 + self._n_cache))
                  if _donation_enabled() else ())
        return jax.jit(decode, donate_argnums=donate)

    def cancel(self, request_id):
        """Base cancel plus the injection queue (a handed-off request
        cancelled before its pages land)."""
        out = super().cancel(request_id)
        if out is not None:
            return out
        for req in self._inject_queue:
            if req.id == request_id:
                self._inject_queue.remove(req)
                self._g_queue.set(self._queued_total())
                self._inc("requests_cancelled")
                return req
        return None

    def active_request_ids(self):
        """Base ids plus the injection queue (handed-off requests whose
        pages landed but haven't been admitted yet are still owned
        here — a relaunched router must not re-ship them)."""
        ids = super().active_request_ids()
        seen = set(ids)
        ids += [str(r.id) for r in self._inject_queue
                if str(r.id) not in seen]
        return ids

    # -------------------------------------------------------------- warmup
    def _warmup_wave_len(self, lo, s, mnt):
        """Rungs only reachable by chunk-eligible prompts stay cold on
        the WAVE path (the chunked executable covers those admissions);
        rungs short prompts can still bucket up into get a warmup
        prompt capped at ``prefill_chunk`` so it is not diverted."""
        n = super()._warmup_wave_len(lo, s, mnt)
        if self._prefill_chunk is None:
            return n
        if lo > self._prefill_chunk:
            return None             # every prompt this long chunks
        return min(n, self._prefill_chunk)

    def warmup(self, max_new_tokens=2):
        """Base ladder + decode warmup, plus the paged extras: the COW
        copy executable and (when chunking is on) the chunk executable,
        so steady traffic compiles NOTHING even on first divergence or
        first long prompt.  Artifact-preloaded executables skip their
        warmup work like the base ladder's do.  Warmup's synthetic
        prompt pages are flushed from the prefix cache afterwards —
        they must not shadow real traffic's hits or hold pages."""
        before = self._counts["prefill_compiles"]
        super().warmup(max_new_tokens)
        self._warming = True
        real_max_queue = self.max_queue
        self.max_queue = max(real_max_queue, self.slots,
                             self.batch_buckets[-1])
        try:
            if (self._copy_jit is None
                    and not _cc.artifact_ready(
                        self._aot_key("copy"),
                        topology=self._topology())):
                # scratch-onto-scratch: a no-op copy that only compiles
                # (with an artifact on disk the load happens lazily at
                # the first real COW — a deserialization, not a compile)
                self._set_cache(self._get_copy_jit()(
                    *self._cache_operands(), np.int32(0), np.int32(0)))
            if (self._chunk_jit is None
                    and self._prefill_chunk is not None
                    and self._prefill_chunk + 2 <= self.max_len
                    and not _cc.artifact_ready(
                        self._aot_key("chunk", c=self._prefill_chunk),
                        topology=self._topology())):
                n = self._prefill_chunk + 1      # two chunks: full + tail
                self.submit(np.ones((n,), np.int32), 1)
                self.run()
            if self._handoff or self._host_tier is not None:
                # prime the handoff executables so a disaggregated
                # replica's first extraction/injection is not a compile
                # in live traffic: a scratch-table extract and a
                # zero-payload inject aimed at the scratch page.  A
                # host-tier engine primes BOTH too — spills ride the
                # extract, fault-backs ride the inject
                if (self._extract_jit is None
                        and not _cc.artifact_ready(
                            self._aot_key("extract"),
                            topology=self._topology())):
                    self._extract_slot_kv(0, 0)
                if (self._inject_jit is None
                        and not _cc.artifact_ready(
                            self._aot_key("inject"),
                            topology=self._topology())):
                    zeros = [np.zeros(
                        (p.shape[0], 0) + tuple(p.shape[2:]),
                        np.dtype(p.dtype))
                        for p in self._cache_operands()]
                    self._inject_call(
                        np.zeros((self._pages_per_slot,), np.int32),
                        self._pad_payload(zeros, 0))
        finally:
            self._warming = False
            self.max_queue = real_max_queue
        self._pager.flush_reclaimable()
        return self._counts["prefill_compiles"] - before

    # --------------------------------------------------------------- views
    def _kv_accounting(self):
        """Paged accounting: reserved = pages actually referenced (the
        whole point — idle capacity costs nothing); ``page_utilization``
        is tokens held per in-use page position and can exceed 1.0 when
        prefix sharing packs several requests onto one physical page.

        Bytes derive from the ACTUAL cache arrays (``nbytes``), never an
        assumed 4-byte element — an int8 pool's pages cost 1 byte per
        element PLUS their per-position-per-head scale rows, and both
        halves of that pair count (a page without its scales is not a
        page)."""
        ps = self._page_size
        total = sum(int(a.nbytes) for a in self._cache_operands())
        page_bytes = total // self._num_pages
        in_use = self._pager.pages_in_use()
        held = int(self._lens.sum()) + sum(
            int(getattr(r, "_chunk_pos", 0)) for r in self._chunk_jobs)
        return {"kv_bytes_reserved": int(in_use * page_bytes),
                "kv_bytes_total": total,
                "kv_tokens_held": held,
                "page_utilization": round(held / max(1, in_use * ps), 4)}

    def stats(self):
        # queue_depth comes through _queued_total (inject queue
        # included): drivers polling it — the fleet worker's step loop
        # — must see queued handoffs or a decode replica never steps
        out = super().stats()
        pg = self._pager.stats()
        for k in ("prefix_page_hits", "prefix_page_misses", "cow_copies"):
            pg.pop(k)    # the engine-mirrored (warmup-quiet) counts win
        out.update(pg)
        tier = self._host_tier
        out["host_tier_bytes"] = int(tier.bytes) if tier else 0
        out["host_tier_entries"] = len(tier) if tier else 0
        out["host_tier_fill"] = (
            round(tier.bytes / max(1, tier.limit), 4)
            if tier else 0.0)
        if tier:
            self._g_host_tier.set(tier.bytes)
        # the replica's prefix sketch for the fleet router: short
        # digests of resident (device) and spilled (host) full-page
        # chains, deduped, newest-biased, wire-bounded
        digests = list(self._pager.chain_digests(limit=128))
        if tier:
            digests.extend(tier.digests(limit=64))
        seen, sketch = set(), []
        for d in reversed(digests):
            if d not in seen:
                seen.add(d)
                sketch.append(d)
        out["chain_digests"] = sketch[:160]
        return out
