"""Block-table KV page allocator (ISSUE 8 tentpole).

The PR-5 engine reserved one contiguous ``[max_len]`` KV strip per slot
whether or not it was used — admission capacity was ``slots`` no matter
how short the requests, and every idle position was dead HBM.  This
module is the vLLM-shaped fix (Kwon et al., SOSP 2023): the KV pool is a
fixed set of ``num_pages`` pages of ``page_size`` tokens, and each slot
holds a *page table* (a list of physical page ids) that grows on demand,
so a 12-token request pins two 8-token pages, not a 96-token strip.

On top of plain paging it does SGLang/RadixAttention-style
**shared-prefix reuse**: prompt pages are keyed by a running content
hash (chain of full-page token blocks; the partial tail page keys on the
chain digest *plus* its token tuple), identical prefixes map to the same
physical pages with a reference count, and a released request's prompt
pages are *retained* on an LRU reclaim list instead of freed — a later
request with the same system prompt re-acquires them without allocating
any new pages.  (Sharing saves *memory*, not FLOPs: the sharer's prefill
still recomputes and rewrites the identical content.)  Divergence is
handled by **copy-on-write**: appending a token into a page someone else
also holds — or into a prefix-registered page, which stays frozen at its
prompt-only content so future sharers are never exposed to live decode
state — first moves the writer onto a private copy (the engine performs
the device-side copy; the pager only does the bookkeeping and says which
page to copy).

The pager is pure host-side bookkeeping — no jax imports — so it is
unit-testable without a backend and never shows up in a trace.  Page 0
is reserved as the *scratch* page: inactive decode lanes and padded
prefill rows scatter their garbage there, where nothing ever reads it.

Invariants:

* ``ref[p] >= 1`` for every page in some table; exactly the pages with
  ``ref == 0`` are on the free list or the reclaim LRU.
* A page is written only by (a) the prefill of prompts whose content
  hashes to it — identical bytes for every prompt-covered position by
  construction, with nothing live past them (registered pages are
  frozen, see :meth:`KVPager.ensure_append`) — or (b) the single slot
  that owns it exclusively (``ref == 1``, unregistered) at append time;
  COW restores private ownership before any divergent write.
* Exhaustion raises :class:`PagesExhausted` *after rolling back* any
  partial acquisition, so a failed admit never leaks pages.
"""
from __future__ import annotations

import collections
import hashlib

import numpy as np

__all__ = ["KVPager", "PagesExhausted", "SCRATCH_PAGE",
           "prompt_chain_keys", "prompt_head_digest", "short_digest"]

SCRATCH_PAGE = 0

# compact digest width for the fleet prefix index (ISSUE 17): 12 hex
# chars of a 128-bit blake2b — short enough that a replica's whole
# sketch rides every step-stats reply, long enough that accidental
# collisions cost only a mis-routed (still correct) dispatch
SHORT_DIGEST_LEN = 12


def prompt_chain_keys(prompt, page_size, hash_key=""):
    """One content key per page of ``prompt`` (module-level so the
    ROUTER — which never imports jax or builds an engine — computes the
    IDENTICAL keys a replica's pager does): full pages key on the
    running chain digest (prefix-identity, not page-identity: the same
    tokens after a different prefix are a different page); the partial
    tail keys on the digest *plus* its token tuple.  ``hash_key`` is
    the numeric-contract salt (quant mode / kv_dtype)."""
    toks = np.asarray(prompt, np.int64).reshape(-1)
    ps = int(page_size)
    h = hashlib.blake2b(digest_size=16)
    if hash_key:
        h.update(str(hash_key).encode())
    keys = []
    for j in range(0, len(toks), ps):
        chunk = toks[j:j + ps]
        if len(chunk) == ps:
            h.update(chunk.tobytes())
            keys.append(("full", h.hexdigest()))
        else:
            keys.append(("part", h.hexdigest(),
                         tuple(int(t) for t in chunk)))
    return keys


def short_digest(key):
    """A content key's compact wire form for the fleet prefix index, or
    None for partial-tail keys (only FULL pages are sticky-routable —
    a tail's bytes change with every prompt length)."""
    if key[0] != "full":
        return None
    return key[1][:SHORT_DIGEST_LEN]


def prompt_head_digest(prompt, page_size, hash_key=""):
    """The compact digest of ``prompt``'s FIRST full page (the sticky-
    routing key: requests sharing their head page share their whole
    cached prefix chain's root), or None for prompts shorter than one
    page."""
    toks = np.asarray(prompt, np.int64).reshape(-1)
    ps = int(page_size)
    if len(toks) < ps:
        return None
    h = hashlib.blake2b(digest_size=16)
    if hash_key:
        h.update(str(hash_key).encode())
    h.update(toks[:ps].tobytes())
    return h.hexdigest()[:SHORT_DIGEST_LEN]


class PagesExhausted(RuntimeError):
    """The pool has no free or reclaimable page left.  The engine's
    policy on catching this is *preempt the newest request* (its pages
    go back to the pool, the request re-queues from its prompt) — named,
    counted, never a silent stall."""


class KVPager:
    """Free-list page allocator with ref-counted prefix sharing.

    ``num_pages`` counts the whole pool *including* the reserved scratch
    page 0, so ``num_pages - 1`` pages are allocatable.  ``tables[s]``
    is slot ``s``'s ordered list of physical page ids; page ``j`` holds
    token positions ``[j*page_size, (j+1)*page_size)`` of that slot's
    sequence."""

    def __init__(self, num_pages, page_size, slots, prefix_cache=True,
                 hash_key=None):
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.slots = int(slots)
        self.prefix_cache = bool(prefix_cache)
        # the numeric contract under the page bytes (quant mode,
        # kv_dtype — ISSUE 9): salted into every content hash so pages
        # from engines with different numeric contracts can never be
        # mistaken for one another (a fleet comparing prefix keys across
        # mixed fp32/int8 replicas must never alias them)
        self.hash_key = "" if hash_key is None else str(hash_key)
        if self.num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is scratch), got "
                f"{num_pages}")
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.free = collections.deque(range(1, self.num_pages))
        self.ref = [0] * self.num_pages
        self.tables = [[] for _ in range(self.slots)]
        self._cache = {}                    # content key -> page id
        self._cache_gen = 0                 # bumps on any _cache mutation
        self._digest_sketch = (None, None)  # (gen, digests) memo
        self._page_key = {}                 # page id -> content key
        self._reclaim = collections.OrderedDict()   # ref==0, retained
        # host-tier spill hook (ISSUE 17): the engine installs a
        # callable(pid, key) fired when a RETAINED prefix page is
        # evicted out of the reclaim LRU — at call time the page's
        # device bytes are still valid (the caller overwrites them only
        # after _alloc returns), so the engine can capture them into
        # its host tier.  None -> evictions simply discard.
        self.evict_hook = None
        self._pending_keys = [None] * self.slots    # deferred registration
        self._registered = [0] * self.slots         # pages registered so far
        # counters (the engine mirrors these into the serving.* family)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.cow_copies = 0
        self.evictions = 0
        # admission-footprint EMA, the router's pages-per-request signal
        self._ppr_ema = float(max(1, self.pages_for(
            self.page_size * max(1, (self.num_pages - 1) // max(1, self.slots)))))

    # ------------------------------------------------------------- sizing
    def pages_for(self, n_tokens):
        """Pages needed to hold ``n_tokens`` positions."""
        return -(-int(n_tokens) // self.page_size)

    def capacity_tokens(self):
        return (self.num_pages - 1) * self.page_size

    def pages_free(self):
        """Allocatable right now: the free list plus the reclaimable
        (retained, ref==0) prefix pages."""
        return len(self.free) + len(self._reclaim)

    def pages_in_use(self):
        return self.num_pages - 1 - self.pages_free()

    def pages_per_request_est(self):
        return max(1, int(round(self._ppr_ema)))

    # ------------------------------------------------------------ hashing
    def _prompt_keys(self, prompt):
        """One content key per page of ``prompt`` — the module-level
        :func:`prompt_chain_keys` math under this pager's salt (the
        router mirrors it byte-for-byte for sticky routing)."""
        return prompt_chain_keys(prompt, self.page_size, self.hash_key)

    def cached_page(self, key):
        """The physical page currently holding ``key``'s content, or
        None — the engine's fault-back probe (device hit vs host
        tier)."""
        return self._cache.get(key) if self.prefix_cache else None

    def chain_digests(self, limit=128):
        """The compact digests of the FULL prompt pages this pager can
        serve as prefix hits right now (registered, device-resident —
        shared or retained), newest-registered last, capped at
        ``limit``.  This is the per-replica sketch each step-stats
        reply ships to the router's fleet prefix index."""
        gen, memo = self._digest_sketch
        if gen != self._cache_gen:
            memo = [d for d in map(short_digest, self._cache)
                    if d is not None]
            self._digest_sketch = (self._cache_gen, memo)
        return memo[-int(limit):]

    # --------------------------------------------------------- allocation
    def _alloc(self):
        if self.free:
            return self.free.popleft()
        if self._reclaim:
            # evict the least-recently-retained prefix page
            pid, _ = self._reclaim.popitem(last=False)
            key = self._page_key.pop(pid, None)
            if key is not None:
                self._cache.pop(key, None)
                self._cache_gen += 1
                if self.evict_hook is not None:
                    # the page's device bytes are still intact HERE —
                    # the caller only overwrites them after we return —
                    # so the host-tier spill capture must be synchronous
                    # with the eviction
                    self.evict_hook(pid, key)
            self.evictions += 1
            return pid
        raise PagesExhausted(
            f"KV page pool exhausted: {self.num_pages - 1} pages all "
            f"referenced ({sum(1 for r in self.ref[1:] if r)} in tables)")

    def _decref(self, pid):
        self.ref[pid] -= 1
        assert self.ref[pid] >= 0, (pid, self.ref[pid])
        if self.ref[pid] == 0:
            if pid in self._page_key and self.prefix_cache:
                self._reclaim[pid] = True      # retained for prefix reuse
                self._reclaim.move_to_end(pid)
            else:
                self.free.append(pid)

    def _acquire_cached(self, pid):
        if self.ref[pid] == 0:
            self._reclaim.pop(pid, None)
        self.ref[pid] += 1

    # ------------------------------------------------------------- admit
    def admit(self, slot, prompt, defer_register=False):
        """Acquire the page table for ``prompt`` in ``slot``: prefix
        pages whose content hash is already cached are *shared*
        (ref-count bumped, zero new pages); the rest are freshly
        allocated.  Returns ``(table, hits)``.

        With ``defer_register`` (chunked prefill) the fresh pages are
        NOT entered into the prefix cache yet — their K/V content does
        not exist until the chunks run — call :meth:`register_prompt`
        after each chunk lands.  On exhaustion the partial acquisition
        is rolled back and :class:`PagesExhausted` propagates."""
        if self.tables[slot]:
            raise RuntimeError(f"slot {slot} already holds pages")
        keys = self._prompt_keys(prompt)
        taken, hits = [], 0
        try:
            for key in keys:
                pid = self._cache.get(key) if self.prefix_cache else None
                if pid is not None:
                    self._acquire_cached(pid)
                    hits += 1
                else:
                    pid = self._alloc()
                    self.ref[pid] = 1
                    if self.prefix_cache and not defer_register:
                        self._register(pid, key)
                taken.append(pid)
        except PagesExhausted:
            for pid in taken:
                self._decref(pid)
            raise
        self.tables[slot] = taken
        self.prefix_hits += hits
        self.prefix_misses += len(taken) - hits
        if defer_register:
            self._pending_keys[slot] = keys
            self._registered[slot] = 0       # re-registering a shared
            # page is a no-op (_register keeps the oldest mapping), so
            # starting from 0 is safe even when some pages were hits
        self._ppr_ema = 0.75 * self._ppr_ema + 0.25 * len(taken)
        return taken, hits

    def admit_pinned(self, slot, prompt):
        """Two-pass admit for the engine's host-tier fault-back (ISSUE
        17): acquire every device-cached page FIRST — pinning it (ref
        >= 1) so the second pass's fresh allocations can never evict it
        out of the reclaim LRU mid-admission — then allocate+register
        pages for the missing keys.  Returns ``(table, hit_flags)``
        where ``hit_flags[j]`` is True for device-shared pages and
        False for freshly allocated ones (whose bytes the engine
        injects from its host tier).  Rolls back on exhaustion exactly
        like :meth:`admit`."""
        if self.tables[slot]:
            raise RuntimeError(f"slot {slot} already holds pages")
        keys = self._prompt_keys(prompt)
        table = [None] * len(keys)
        hit_flags = [False] * len(keys)
        taken = []
        try:
            for j, key in enumerate(keys):
                pid = self._cache.get(key) if self.prefix_cache else None
                if pid is not None:
                    self._acquire_cached(pid)
                    table[j] = pid
                    hit_flags[j] = True
                    taken.append(pid)
            for j, key in enumerate(keys):
                if table[j] is not None:
                    continue
                pid = self._alloc()
                self.ref[pid] = 1
                if self.prefix_cache:
                    self._register(pid, keys[j])
                table[j] = pid
                taken.append(pid)
        except PagesExhausted:
            for pid in taken:
                self._decref(pid)
            raise
        self.tables[slot] = table
        hits = sum(hit_flags)
        self.prefix_hits += hits
        self.prefix_misses += len(table) - hits
        self._ppr_ema = 0.75 * self._ppr_ema + 0.25 * len(table)
        return table, hit_flags

    def _register(self, pid, key):
        old = self._cache.get(key)
        if old is not None and old != pid:
            # a concurrent identical prompt registered first; keep the
            # oldest mapping (its content is just as valid)
            return
        self._cache[key] = pid
        self._cache_gen += 1
        self._page_key[pid] = key

    def register_prompt(self, slot, upto_tokens):
        """Enter this slot's prompt pages into the prefix cache once
        their content actually exists on device — pages fully covered by
        ``upto_tokens``, plus the partial tail when the whole prompt is
        in.  No-op for non-deferred admissions."""
        keys = self._pending_keys[slot]
        if keys is None or not self.prefix_cache:
            return
        ps = self.page_size
        table = self.tables[slot]
        for j in range(self._registered[slot], len(keys)):
            full = (j + 1) * ps <= upto_tokens
            tail_done = (keys[j][0] == "part"
                         and upto_tokens >= (len(keys) - 1) * ps)
            if not (full or tail_done):
                break
            self._register(table[j], keys[j])
            self._registered[slot] = j + 1
        if self._registered[slot] >= len(keys):
            self._pending_keys[slot] = None

    # ------------------------------------------------------------- append
    def ensure_append(self, slot, pos):
        """Make position ``pos`` of ``slot`` writable; returns
        ``(page_id, offset, cow_src)``.  Allocates a fresh tail page on
        a page boundary; if the tail page is shared (``ref > 1``) OR
        prefix-registered, the slot is moved onto a private copy first
        and ``cow_src`` names the page whose contents the engine must
        copy device-side before the write.

        The registered-page case is load-bearing: a cache-registered
        tail page is FROZEN at its prompt-only content.  If the owner
        appended decode tokens into it in place, a later identical
        prompt would share a page whose positions past the prompt hold
        live generated K/V — and that request's prefill rewrites whole
        pages, clobbering the owner's sequence.  COW-on-first-append
        keeps the cached page pristine (it retires to the reclaim list
        at ref 0), so sharers only ever rewrite prompt-identical bytes
        plus positions nobody has real data at.  Idempotent for the
        same ``(slot, pos)``."""
        ps = self.page_size
        j, off = divmod(int(pos), ps)
        table = self.tables[slot]
        if j == len(table):
            pid = self._alloc()
            self.ref[pid] = 1
            table.append(pid)
            return pid, off, None
        if j > len(table):
            raise RuntimeError(
                f"append at position {pos} skips pages (slot {slot} "
                f"holds {len(table)} pages of {ps})")
        pid = table[j]
        if self.ref[pid] > 1 or (self.prefix_cache
                                 and pid in self._page_key):
            dst = self._alloc()
            self.ref[dst] = 1
            self._decref(pid)
            table[j] = dst
            self.cow_copies += 1
            return dst, off, pid
        return pid, off, None

    def ensure_append_window(self, slot, pos, n):
        """Speculative multi-token append (ISSUE 13): make positions
        ``pos .. pos + n - 1`` of ``slot`` writable in one walk —
        allocating every tail page the window crosses and COWing a
        shared/frozen tail exactly like :meth:`ensure_append` (whose
        idempotence this inherits: re-walking after a preemption retry
        is safe, and pages pre-allocated for a window the verify then
        only partially committed are simply reused by the next window).
        Returns ``(pids [n], offs [n], cows)`` where ``cows`` is a list
        of ``(src, dst)`` pairs the engine must copy device-side before
        any write.  On exhaustion the already-ensured prefix stays owned
        by the slot (released wholesale if the slot is preempted) and
        :class:`PagesExhausted` propagates."""
        pids, offs, cows = [], [], []
        for d in range(int(n)):
            pid, off, cow = self.ensure_append(slot, int(pos) + d)
            if cow is not None:
                cows.append((cow, pid))
            pids.append(pid)
            offs.append(off)
        return pids, offs, cows

    # ------------------------------------------------------------ release
    def release(self, slot):
        """Drop the slot's table.  Pages fall to ref 0 and either retire
        to the reclaim LRU (prompt pages, prefix cache on) or the free
        list (generated-token pages)."""
        for pid in self.tables[slot]:
            self._decref(pid)
        self.tables[slot] = []
        self._pending_keys[slot] = None
        self._registered[slot] = 0

    def flush_reclaimable(self):
        """Evict every retained prefix page (e.g. after warmup, so the
        synthetic prompts don't shadow real traffic's cache)."""
        n = 0
        while self._reclaim:
            pid, _ = self._reclaim.popitem(last=False)
            key = self._page_key.pop(pid, None)
            if key is not None:
                self._cache.pop(key, None)
                self._cache_gen += 1
            self.free.append(pid)
            n += 1
        return n

    # -------------------------------------------------------------- views
    def table_array(self, slot, width):
        """The slot's table as a fixed-width int32 row (scratch-padded)
        for the device page-table tensor."""
        row = np.zeros((width,), np.int32)
        t = self.tables[slot]
        row[:len(t)] = t
        return row

    def stats(self):
        return {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "pages_in_use": self.pages_in_use(),
            "pages_free": self.pages_free(),
            "pages_reclaimable": len(self._reclaim),
            "free_page_fraction": round(
                self.pages_free() / max(1, self.num_pages - 1), 4),
            "prefix_page_hits": self.prefix_hits,
            "prefix_page_misses": self.prefix_misses,
            "cow_copies": self.cow_copies,
            "evictions": self.evictions,
            "pages_per_request_est": self.pages_per_request_est(),
            "chain_digest_count": len(self._cache),
        }
