"""Predictor API (ref: paddle/fluid/inference/api/analysis_predictor.cc,
paddle_inference_api.h, python/paddle/inference/__init__.py).

Three model sources load into the same Predictor:

  * standalone StableHLO (inference/export.py::save_inference_model) —
    parameters baked in, loadable in a fresh process with no Python class
    (the analogue of the reference's frozen __model__ + params); named
    input/output handles come from the .pdmeta manifest.  Calls go
    through StandaloneModel's per-shape-signature executable cache
    (counted in ``serving.standalone_compiles``).
  * jit.save pickles (.pdmodel/.pdiparams) — in-ecosystem reload of a
    Layer; re-traced on first run.
  * an in-memory Layer (``Predictor.from_layer``) — serve a model you
    just trained without a save/load round-trip; compile reuse rides the
    eager dispatch cache.

Config genuinely selects the execution device; the reference's IR pass
pipeline (fusion, memory planning) is XLA's job here.  With
``PADDLE_JIT_CACHE_DIR`` set, compiled executables persist across
processes (framework/jax_compat.py::enable_persistent_cache), so a
predictor restart skips every retrace.
"""
from __future__ import annotations

import numpy as np
import jax

from ..framework import jax_compat
from ..jit import api as jit_api
from ..tensor.tensor import Tensor
from . import export as export_mod
from .export import StandaloneModel


class Config:
    """ref paddle_inference_api.h::AnalysisConfig — device selection and
    optimization toggles (the latter are XLA's defaults here)."""

    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path
        self._device = None          # None -> default platform
        self._memory_pool_mb = 0
        self._ir_optim = True

    # -- device selection (really honored by Predictor) --
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        """Accelerator request: maps to the TPU platform."""
        self._device = "tpu"
        self._memory_pool_mb = memory_pool_init_size_mb

    def enable_tpu(self):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def device(self):
        """Resolved jax device (or None for platform default)."""
        if self._device is None:
            return None
        for d in jax.devices():
            if d.platform == self._device:
                return d
        if self._device == "cpu":
            return jax.devices("cpu")[0]
        return None

    # -- optimization toggles: XLA always fuses/plans; kept for parity --
    def enable_memory_optim(self):
        self._ir_optim = True

    def switch_ir_optim(self, flag=True):
        self._ir_optim = bool(flag)

    def set_cpu_math_library_num_threads(self, n):
        self._num_threads = int(n)


class Predictor:
    def __init__(self, config, _layer=None):
        if isinstance(config, str):
            config = Config(config)
        self._config = config
        jax_compat.enable_persistent_cache()
        dev = config.device()
        self._device = dev
        self._layer = None
        self._model = None
        self._traced = None
        if _layer is not None:
            self._layer = _layer
            self._layer.eval()
            n_in = getattr(config, "_n_inputs", 1)
            self._in_names = [f"x{i}" for i in range(n_in)]
            self._out_names = ["out0"]
        else:
            path = config.model_path
            if path is None:
                raise ValueError(
                    "Config has no model_path — pass an artifact prefix "
                    "(save_inference_model / jit.save output) or use "
                    "Predictor.from_layer(layer) for an in-memory model")
            if path.endswith(jit_api._JIT_SUFFIX):
                path = path[: -len(jit_api._JIT_SUFFIX)]
            if export_mod.exists(path):
                self._model = StandaloneModel(path, device=dev)
                self._in_names = self._model.input_names()
                self._out_names = self._model.output_names()
            else:
                self._traced = jit_api.load(path)
                self._traced._layer.eval()
                meta = getattr(self._traced, "_meta", None) or {}
                n_in = len(meta.get("input_spec", [])) or 1
                self._in_names = [f"x{i}" for i in range(n_in)]
                self._out_names = ["out0"]
        self._inputs = {}
        self._outputs = None

    @classmethod
    def from_layer(cls, layer, config=None, n_inputs=1):
        """Serve an IN-MEMORY Layer (no artifact round-trip): the eager
        dispatch cache gives per-signature compile reuse, so repeated
        same-shape calls don't retrace."""
        config = config or Config()
        config._n_inputs = int(n_inputs)
        return cls(config, _layer=layer)

    # -- named IO handles (ref: GetInputHandle/GetOutputHandle) --
    def get_input_names(self):
        return list(self._in_names)

    def get_output_names(self):
        return list(self._out_names)

    def get_input_handle(self, name):
        if name not in self._in_names:
            raise KeyError(f"unknown input '{name}'; have {self._in_names}")
        return _Handle(self, name)

    def get_output_handle(self, name):
        if name not in self._out_names:
            raise KeyError(
                f"unknown output '{name}'; have {self._out_names}")
        return _OutHandle(self, self._out_names.index(name))

    def run(self, inputs=None):
        if inputs is not None:
            if len(inputs) != len(self._in_names):
                raise ValueError(
                    f"got {len(inputs)} inputs for {len(self._in_names)} "
                    f"input handles {self._in_names}; for an in-memory "
                    "layer declare the arity with "
                    "Predictor.from_layer(net, n_inputs=N)")
            self._inputs = {n: np.asarray(x.numpy() if isinstance(x, Tensor)
                                          else x)
                            for n, x in zip(self._in_names, inputs)}
        ordered = [self._inputs[n] for n in self._in_names]
        if self._model is not None:
            outs = self._model(*ordered)
            self._outputs = [np.asarray(o) for o in outs]
        else:
            args = [Tensor(jax.device_put(o, self._device)
                           if self._device is not None else o)
                    for o in ordered]
            runner = self._layer if self._layer is not None else self._traced
            out = runner(*args)
            outs = out if isinstance(out, (list, tuple)) else [out]
            self._outputs = [o.numpy() for o in outs]
        if len(self._outputs) != len(self._out_names):
            # jit-pickle / in-memory paths don't record the output arity;
            # grow the handle names to one per REAL output on first run
            self._out_names = [f"out{i}"
                               for i in range(len(self._outputs))]
        return self._outputs


class _Handle:
    def __init__(self, predictor, name):
        self.predictor = predictor
        self.name = name
        self._shape = None

    def copy_from_cpu(self, arr):
        arr = np.asarray(arr)
        if self._shape is not None:
            arr = arr.reshape(self._shape)
        self.predictor._inputs[self.name] = arr

    def reshape(self, shape):
        self._shape = tuple(shape)


class _OutHandle:
    def __init__(self, predictor, index):
        self.predictor = predictor
        self.index = index

    def copy_to_cpu(self):
        return self.predictor._outputs[self.index]


def create_predictor(config):
    return Predictor(config)
