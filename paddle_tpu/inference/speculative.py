"""Speculative decoding inside the one donated decode step (ISSUE 13).

Every decode iteration of :class:`~.serving.PagedServingEngine` emits
exactly one token per sequence — token latency is one full target
forward per token.  This module recovers >1 token per target forward
(Leviathan et al., "Fast Inference from Transformers via Speculative
Decoding"; Saxena, "Prompt Lookup Decoding") while keeping every
serving invariant earned in PRs 5/8/9:

* **one donated decode executable, forever** — each iteration drafts
  ``spec_k`` candidate tokens per active row, then ONE jitted,
  buffer-donated VERIFY step scores all ``k+1`` window positions in a
  single batched target forward, computes the longest accepted prefix
  IN-GRAPH (accept length is a traced value — there is no compile per
  accept length), and commits it with a masked page-aligned scatter.
  ``decode_compiles`` stays 1; draft mode adds exactly two more
  executables (``spec_draft_compiles``: the draft prefill chunk and the
  fused catch-up+draft-k step), a fixed set warmup covers.
* **rejected tokens never corrupt paged KV** — the verify forward is
  deferred-commit (models/gpt.py::decode_step_paged_verify): the page
  pool is read-only during the forward, and the commit scatter
  redirects every rejected window lane to the scratch page.  Accepted
  positions land the exact bytes (and, on the int8 pool, the exact
  once-per-position scales) a sequential decode would have written, so
  the prefix-hash/page-byte determinism contract survives — the
  ``spec_reject`` fault's regression test proves an all-reject verify
  leaves the pool byte-identical to a never-speculated run.
* **token-exact greedy output** — accepted drafts equal the verify's
  own argmax by construction, and the bonus token IS that argmax, so
  the committed stream is exactly what the non-speculative engine
  would emit, through churn, chunked prefill, preemption-retry, and
  ``kv_dtype="int8"``.

Two drafting modes:

* ``"ngram"`` — model-free prompt-lookup: draft the continuation of
  the most recent earlier occurrence of the row's trailing n-gram in
  its OWN token history (the host mirror of its paged KV contents:
  prompt + committed tokens).  Pure numpy over host-resident ints — it
  adds ZERO device syncs and zero executables.  On the repetitive /
  shared-prefix traffic a production fleet actually sees (and on
  greedy decoding's attractor cycles) this alone sustains multiple
  accepted tokens per verify.
* ``"draft"`` — a small seeded draft GPT (its own slot-contiguous KV
  cache, ``2*spec_k`` positions deeper than the target's ``max_len``)
  proposes the k candidates; each iteration one fused executable
  catches the draft cache up on last step's committed tokens and
  self-samples the next k (models/gpt.py::draft_catchup_and_draft).
  Draft K/V past the committed length are speculative garbage masked
  by the fill bound, overwritten by the next catch-up — the draft
  cache needs no rollback machinery.

Knobs (constructor args, with ``PADDLE_SPEC_*`` env fallbacks):
``spec_mode`` (env ``PADDLE_SPEC_MODE``, default "ngram"), ``spec_k``
(``PADDLE_SPEC_K``, default 4), ``spec_ngram_max``
(``PADDLE_SPEC_NGRAM``, default 3), ``spec_draft_cfg`` /
``spec_draft_seed`` (``PADDLE_SPEC_DRAFT_SEED``, default 0).

Telemetry rides the ``serving.*`` family: ``drafted_tokens`` /
``accepted_tokens`` / ``rejected_tokens`` / ``spec_steps`` counters,
the ``serving.accepted_tokens_per_step`` gauge (committed tokens per
row-verify, the >1 speedup factor bench.py asserts), and
``serving_step`` JSONL events carry ``drafted``/``accepted``/
``committed`` fields.
"""
from __future__ import annotations

import dataclasses
import os
import time

import numpy as np

from ..framework import compile_cache as _cc
from ..models import gpt
from ..observability import metrics, timeline, tracing
from ..testing import faults as _faults
from .serving import PagedServingEngine, _donation_enabled

__all__ = ["SpeculativeServingEngine", "ngram_draft", "accept_commit",
           "SPEC_MODES"]

SPEC_MODES = ("draft", "ngram")


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# --------------------------------------------------------------------------
# model-free drafting: prompt lookup / n-gram continuation
# --------------------------------------------------------------------------

def ngram_draft(history, k, max_ngram=3):
    """Prompt-lookup drafting (Saxena): find the most recent EARLIER
    occurrence of the trailing ``n``-gram of ``history`` (trying
    ``max_ngram`` down to 1) and draft the ``k`` tokens that followed
    it; pad with the last drafted (or last history) token when the
    match sits near the end.  No match at any n: draft the last token
    repeated (a cheap guess — wrong drafts cost nothing but their lane
    of an already-paid verify).

    Pure numpy over the HOST-side token mirror (prompt + committed
    tokens) — the matcher never touches device values, so it introduces
    no host-sync into the decode loop."""
    h = np.asarray(history, np.int64).reshape(-1)
    k = int(k)
    if h.size == 0:
        return np.zeros((k,), np.int32)
    for n in range(min(int(max_ngram), h.size - 1), 0, -1):
        pat = h[-n:]
        # candidate windows live in h[:-1]: every length-n window whose
        # continuation exists and which is not the trailing n-gram
        # itself (sliding over h[:-1] excludes it by construction)
        win = np.lib.stride_tricks.sliding_window_view(h[:-1], n)
        hits = np.nonzero((win == pat[None, :]).all(axis=1))[0]
        if hits.size:
            s = int(hits[-1])
            cont = h[s + n:s + n + k]
            out = np.empty((k,), np.int64)
            out[:cont.size] = cont
            out[cont.size:] = cont[-1]
            return out.astype(np.int32)
    return np.full((k,), int(h[-1]), np.int32)


# --------------------------------------------------------------------------
# accept / commit math (traced; unit-tested directly)
# --------------------------------------------------------------------------

def accept_commit(drafts, greedy, caps, eos_ids, force_reject):
    """The longest-accepted-prefix commit math, all traced values so it
    lives INSIDE the one donated verify executable.

    ``drafts`` int32 [S, k] (the candidates, window positions 1..k);
    ``greedy`` int32 [S, k+1] (the verify's argmax at every window
    position); ``caps`` int32 [S] (commit budget: remaining
    ``max_new_tokens``, clipped to k+1; 0 silences an inactive row);
    ``eos_ids`` int32 [S] (-1 = no eos); ``force_reject`` int32 scalar
    (the ``spec_reject`` fault: accept length forced to 0).

    Returns ``(out_toks [S, k+1], n_commit [S])``: the committed stream
    is ``out_toks[s, :n_commit[s]]``.  Accepted drafts equal the greedy
    row by definition, and the bonus token is ``greedy[accept_len]``,
    so ``out_toks`` IS the greedy row — token-exactness with the
    non-speculative engine is by construction, not by comparison.
    ``n_commit`` truncates at the commit budget and at the first eos
    (the eos commits, nothing after it — and critically nothing after
    it is K/V-committed either)."""
    import jax.numpy as jnp
    S, W = greedy.shape
    k = W - 1
    if k:
        eq = (drafts == greedy[:, :k]).astype(jnp.int32)
        accept_len = jnp.sum(jnp.cumprod(eq, axis=1), axis=1)
    else:
        accept_len = jnp.zeros((S,), jnp.int32)
    accept_len = jnp.where(force_reject > 0,
                           jnp.zeros_like(accept_len), accept_len)
    pos = jnp.arange(W)[None, :]
    n0 = jnp.minimum(accept_len + 1, caps)
    hit = (greedy == eos_ids[:, None]) & (pos < n0[:, None])
    first = jnp.min(jnp.where(hit, pos, W), axis=1)
    n_commit = jnp.where(first < W, first + 1, n0).astype(jnp.int32)
    return greedy.astype(jnp.int32), n_commit


# --------------------------------------------------------------------------
# the engine
# --------------------------------------------------------------------------

class SpeculativeServingEngine(PagedServingEngine):
    """:class:`~.serving.PagedServingEngine` whose decode step drafts
    ``spec_k`` candidates per row and verifies all ``k+1`` positions in
    ONE donated executable (module docstring has the full contract).
    Greedy output is token-exact with the non-speculative paged engine;
    only the number of target forwards per token changes."""

    def __init__(self, model, *, spec_mode=None, spec_k=None,
                 spec_draft_cfg=None, spec_draft_seed=None,
                 spec_ngram_max=None, spec_draft_chunk=16, **kw):
        mode = spec_mode or os.environ.get("PADDLE_SPEC_MODE", "ngram")
        if mode not in SPEC_MODES:
            raise ValueError(
                f"spec_mode must be one of {SPEC_MODES}, got {mode!r} "
                "(spec_mode=off means: use PagedServingEngine)")
        k = int(spec_k if spec_k is not None
                else _env_int("PADDLE_SPEC_K", 4))
        if k < 1:
            raise ValueError(f"spec_k must be >= 1, got {k}")
        # set before super().__init__: _rebuild_cache (called there)
        # allocates the draft cache from these
        self._spec_mode_val = mode
        self._spec_k_val = k
        self._ngram_max = int(
            spec_ngram_max if spec_ngram_max is not None
            else _env_int("PADDLE_SPEC_NGRAM", 3))
        self._draft_seed = int(
            spec_draft_seed if spec_draft_seed is not None
            else _env_int("PADDLE_SPEC_DRAFT_SEED", 0))
        self._spec_draft_cfg_arg = spec_draft_cfg
        self._draft_chunk = int(spec_draft_chunk)
        self._draft_cfg = None
        self._draft_params = None
        self._draft_k = self._draft_v = None
        self._draft_jit = None
        self._draft_prefill_jit = None
        self._draft_site = _cc.site("serving.draft", maxsize=4)
        self._commit_sum = 0            # committed tokens over live traffic
        self._rowstep_sum = 0           # active rows x verify steps
        super().__init__(model, **kw)
        if self._pp > 1:
            raise ValueError(
                "pp > 1 does not compose with speculative decoding yet "
                "— the verify executable is the GSPMD paged step, not "
                "the 1F1B stage loop (use PagedServingEngine(pp=...))")
        self.spec_mode = mode           # the contract attestation fields
        self.spec_k = k
        self._g_accept = metrics.gauge("serving.accepted_tokens_per_step")

    def _aot_sig(self):
        dc = (",".join(f"{k}={v}" for k, v in sorted(
            dataclasses.asdict(self._draft_cfg).items()))
            if self._draft_cfg is not None else None)
        return (f"{super()._aot_sig()}/spec={self._spec_mode_val}"
                f"/k={self._spec_k_val}/dchunk={self._draft_chunk}"
                f"/dcfg[{dc}]")

    def _aot_has_core(self):
        """The spec engine's decode site holds the VERIFY executable
        (the single-token paged decode never runs here); draft mode
        additionally needs both draft executables before a warmup wave
        may be skipped — a skipped wave with a missing draft artifact
        would push the draft compile into live traffic."""
        topo = self._topology()
        core = _cc.artifact_ready(self._aot_key("verify"), topology=topo)
        if core and self._spec_mode_val == "draft":
            core = (_cc.artifact_ready(
                self._aot_key("draft_prefill", c=self._draft_chunk),
                topology=topo)
                and _cc.artifact_ready(self._aot_key("draft_step"),
                                       topology=topo))
        return core

    # ------------------------------------------------------- draft model
    def _build_draft_cfg(self):
        """The draft GPT config: user-supplied kwargs (or a GPTConfig)
        with ``max_seq_len`` raised to the draft cache's need, or a
        derived half-size default.  The draft's vocab must match the
        target's — its candidates feed the target verify directly."""
        need = self.max_len + 2 * self._spec_k_val
        base = self._spec_draft_cfg_arg
        if base is None:
            c = self.cfg
            heads = max(1, c.num_heads // 2)
            hidden = max(heads, (c.hidden_size // 2 // heads) * heads)
            kwargs = dict(vocab_size=c.vocab_size, hidden_size=hidden,
                          num_layers=max(1, c.num_layers // 2),
                          num_heads=heads, dtype=c.dtype,
                          ffn_size=0)
        elif isinstance(base, gpt.GPTConfig):
            kwargs = dataclasses.asdict(base)
        else:
            kwargs = dict(base)
        kwargs["max_seq_len"] = max(int(kwargs.get("max_seq_len") or 0),
                                    need)
        # the draft decodes through the slot cache's lax math only
        kwargs["use_flash"] = False
        kwargs["remat"] = False
        cfg = gpt.GPTConfig(**kwargs)
        if cfg.vocab_size != self.cfg.vocab_size:
            raise ValueError(
                f"draft vocab_size {cfg.vocab_size} != target "
                f"{self.cfg.vocab_size} — draft candidates feed the "
                "target verify")
        return cfg

    def _rebuild_cache(self):
        super()._rebuild_cache()
        if self._spec_mode_val == "draft":
            if self._draft_cfg is None:
                import jax
                self._draft_cfg = self._build_draft_cfg()
                self._draft_params = gpt.init_params(
                    self._draft_cfg, jax.random.PRNGKey(self._draft_seed))
                if self._mesh is not None:
                    # a tp-sharded target rejects operands committed
                    # off-mesh: the draft model is tiny, so it rides
                    # REPLICATED on the same mesh (its derived head
                    # count need not divide tp)
                    self._draft_params = gpt.replicate_on_mesh(
                        self._draft_params, self._mesh)
            # 2k positions deeper than the target cache: the fused
            # catch-up+draft step writes up to lens + 2k - 1
            dmax = self.max_len + 2 * self._spec_k_val
            cache = gpt.init_slot_cache(self._draft_cfg, self.slots, dmax)
            if self._mesh is not None:
                cache = gpt.replicate_on_mesh(
                    {"k": cache["k"], "v": cache["v"]}, self._mesh)
            self._draft_k, self._draft_v = cache["k"], cache["v"]
        self._draft_lens = np.zeros((self.slots,), np.int32)

    def _build_draft_prefill(self, C):
        jax = self._jax
        dcfg = self._draft_cfg

        def dprefill(params, cache_k, cache_v, toks, slot, offset):
            assert toks.shape == (1, C), (toks.shape, C)  # one chunk exe
            return gpt.draft_prefill_slot(params, toks, dcfg, cache_k,
                                          cache_v, slot, offset)

        donate = (1, 2) if _donation_enabled() else ()
        return jax.jit(dprefill, donate_argnums=donate)

    def _build_draft_step(self):
        jax = self._jax
        dcfg = self._draft_cfg
        k = self._spec_k_val

        def dstep(params, cache_k, cache_v, ctx, n_ctx, lens):
            return gpt.draft_catchup_and_draft(params, dcfg, cache_k,
                                               cache_v, ctx, n_ctx,
                                               lens, k)

        donate = (1, 2) if _donation_enabled() else ()
        return jax.jit(dstep, donate_argnums=donate)

    def _draft_ingest(self, req):
        """Prefill the draft model's cache with the row's prompt (fixed
        C-token chunks through ONE executable) and arm the pending-draft
        backlog with the tokens the target has already committed — at
        activation that is exactly the prefill's first sampled token (a
        preemption retry restarts from the prompt, so it can never be
        mid-history)."""
        jnp = self._jnp
        s = req.slot
        C = self._draft_chunk
        p = np.asarray(req.prompt, np.int32)
        for pos in range(0, len(p), C):
            take = min(C, len(p) - pos)
            toks = np.zeros((1, C), np.int32)
            toks[0, :take] = p[pos:pos + take]
            operands = (self._draft_params, self._draft_k, self._draft_v,
                        jnp.asarray(toks), np.int32(s), np.int32(pos))
            if self._draft_prefill_jit is None:
                donate = (1, 2) if _donation_enabled() else ()
                self._draft_prefill_jit = self._draft_site.get(
                    _cc.make_key("draft_prefill", C, donate=donate,
                                 mesh=self._mesh_key()),
                    lambda: self._build_draft_prefill(C),
                    stable_key=self._aot_key("draft_prefill", c=C),
                    example_args=operands, topology=self._topology())
                self._inc("spec_draft_compiles")
            self._draft_k, self._draft_v = self._draft_prefill_jit(
                *operands)
        self._draft_lens[s] = len(p)
        req.pending_draft = list(req.tokens)

    def _spec_draft_sync(self):
        """Ingest newly-activated rows into the draft cache (draft mode
        only).  ``pending_draft is None`` marks a row the draft has
        never seen this admission — covers wave admissions, chunked
        admissions, and preemption retries uniformly (reset_for_retry
        scrubs it back to None)."""
        if self._spec_mode_val != "draft":
            return
        for s in range(self.slots):
            if not self._active[s]:
                continue
            req = self._slot_req[s]
            if req.pending_draft is None:
                self._draft_ingest(req)

    # ---------------------------------------------------------- drafting
    def _make_drafts(self):
        """The verify window's token matrix [S, k+1] (position 0: the
        last committed token; 1..k: draft candidates) as a device array.
        Draft mode keeps the candidates ON DEVICE (no readback — the
        only host sync of the loop stays the committed-token one)."""
        jnp = self._jnp
        k = self._spec_k_val
        if self._spec_mode_val == "ngram":
            toks = np.zeros((self.slots, k + 1), np.int32)
            for s in range(self.slots):
                if not self._active[s]:
                    continue
                req = self._slot_req[s]
                toks[s, 0] = self._last_tok[s]
                hist = np.concatenate(
                    [req.prompt, np.asarray(req.tokens, np.int32)])
                toks[s, 1:] = ngram_draft(hist, k, self._ngram_max)
            return jnp.asarray(toks)
        # draft mode: catch the draft cache up on last step's committed
        # tokens, then self-sample k candidates — one fused executable
        ctx = np.zeros((self.slots, k + 1), np.int32)
        n_ctx = np.zeros((self.slots,), np.int32)
        for s in range(self.slots):
            if not self._active[s]:
                continue
            pend = self._slot_req[s].pending_draft or []
            ctx[s, :len(pend)] = pend
            n_ctx[s] = len(pend)
        operands = (self._draft_params, self._draft_k, self._draft_v,
                    jnp.asarray(ctx), jnp.asarray(n_ctx),
                    jnp.asarray(self._draft_lens))
        if self._draft_jit is None:
            donate = (1, 2) if _donation_enabled() else ()
            self._draft_jit = self._draft_site.get(
                _cc.make_key("draft_step", k, donate=donate,
                             mesh=self._mesh_key()),
                self._build_draft_step,
                stable_key=self._aot_key("draft_step"),
                example_args=operands, topology=self._topology())
            self._inc("spec_draft_compiles")
        with timeline.span("serving.spec_draft",
                           active=int(self._active.sum())):
            self._draft_k, self._draft_v, drafts = self._draft_jit(
                *operands)
        for s in range(self.slots):
            if self._active[s]:
                self._draft_lens[s] += int(n_ctx[s])
                self._slot_req[s].pending_draft = []
        last = jnp.asarray(self._last_tok)[:, None]
        return jnp.concatenate([last, drafts], axis=1)

    # ------------------------------------------------------------ paging
    def _ensure_spec_pages(self, caps):
        """Writable page coordinates for each row's commit window:
        positions ``lens[s] .. lens[s] + caps[s] - 1`` (the budget-
        capped part — positions past the cap can never commit, their
        lanes redirect to scratch in-graph).  Same preempt-the-newest
        retry loop as the base engine's single-token version."""
        ps = self._page_size
        W = self._spec_k_val + 1
        wpages = np.zeros((self.slots, W), np.int32)
        woffs = np.zeros((self.slots, W), np.int32)
        while True:
            try:
                for s in range(self.slots):
                    wpages[s] = 0
                    woffs[s] = 0
                    if not self._active[s]:
                        continue
                    pos = int(self._lens[s])
                    n = int(caps[s])
                    pids, offs, cows = self._pager.ensure_append_window(
                        s, pos, n)
                    for src, dst in cows:
                        self._copy_page(src, dst)
                    for d, pid in enumerate(pids):
                        self._tables_np[s, (pos + d) // ps] = pid
                    wpages[s, :n] = pids
                    woffs[s, :n] = offs
                return wpages, woffs
            except self._PagesExhausted as e:
                victim = self._newest_victim()
                if victim is None:
                    raise
                self._preempt(victim, str(e))

    # ------------------------------------------------------------ verify
    def _build_verify(self):
        jax, jnp = self._jax, self._jnp
        cfg = self.cfg
        cap = self.capture_logits
        kvq = self._kv_quant
        n = self._n_cache

        def verify(params, *args):
            cache = args[:n]
            (toks, ptab, wpages, woffs, lens, caps, eos_ids,
             force) = args[n:]
            if kvq:
                logits, wk, wks, wv, wvs = gpt.decode_step_paged_verify_quant(
                    params, toks, cfg, *cache, ptab, lens)
            else:
                logits, wk, wv = gpt.decode_step_paged_verify(
                    params, toks, cfg, *cache, ptab, lens)
            greedy = jnp.argmax(logits, -1).astype(jnp.int32)  # [S, W]
            out_toks, n_commit = accept_commit(toks[:, 1:], greedy, caps,
                                               eos_ids, force)
            # masked page-aligned commit: window lane j holds the K/V of
            # the token CONSUMED at position lens+j, valid for exactly
            # j < n_commit; every rejected/padded lane redirects to the
            # scratch page, so the pool's real pages only ever receive
            # the bytes a sequential decode would have written
            mask = jnp.arange(toks.shape[1])[None, :] < n_commit[:, None]
            wp = jnp.where(mask, wpages, 0)
            wo = jnp.where(mask, woffs, 0)
            if kvq:
                out_cache = (cache[0].at[:, wp, wo].set(wk),
                             cache[1].at[:, wp, wo].set(wks),
                             cache[2].at[:, wp, wo].set(wv),
                             cache[3].at[:, wp, wo].set(wvs))
            else:
                out_cache = (cache[0].at[:, wp, wo].set(wk),
                             cache[1].at[:, wp, wo].set(wv))
            out_cache = self._constrain_cache(out_cache)
            if cap:
                return (*out_cache, out_toks, n_commit, logits)
            return (*out_cache, out_toks, n_commit)

        donate = (tuple(range(1, 1 + n)) if _donation_enabled() else ())
        return jax.jit(verify, donate_argnums=donate)

    # ------------------------------------------------------------ driving
    def _step_inner(self):
        self._admit()
        self._advance_chunks()
        if not self._active.any():
            return
        jnp = self._jnp
        k = self._spec_k_val
        W = k + 1
        force_reject = 0
        if _faults.active() and not self._warming:
            if _faults.page_exhaustion_check(
                    step=self._counts["decode_steps"] + 1):
                victim = self._newest_victim()
                if victim is not None:
                    self._preempt(victim, "injected page_exhaustion")
            _faults.engine_step_error(self._counts["decode_steps"] + 1)
            _faults.replica_kill_check(
                step=self._counts["decode_steps"] + 1)
            if _faults.spec_reject_check(
                    step=self._counts["decode_steps"] + 1):
                force_reject = 1
        if not self._active.any():
            return                  # the injected preemption emptied it
        caps = np.zeros((self.slots,), np.int32)
        eos_ids = np.full((self.slots,), -1, np.int32)
        for s in range(self.slots):
            if not self._active[s]:
                continue
            req = self._slot_req[s]
            caps[s] = min(W, req.max_new_tokens - len(req.tokens))
            if req.eos_token is not None:
                eos_ids[s] = int(req.eos_token)
        wpages, woffs = self._ensure_spec_pages(caps)
        if not self._active.any():
            return
        # a mid-ensure preemption freed a slot after its cap was set:
        # silence it so the in-graph commit math treats it as inactive
        caps = np.where(self._active, caps, 0).astype(np.int32)
        self._spec_draft_sync()
        toks_dev = self._make_drafts()
        operands = (self.params, *self._cache_operands(), toks_dev,
                    jnp.asarray(self._tables_np), jnp.asarray(wpages),
                    jnp.asarray(woffs), jnp.asarray(self._lens),
                    jnp.asarray(caps), jnp.asarray(eos_ids),
                    np.int32(force_reject))
        if self._decode_jit is None:
            donate = self._donate()
            self._decode_jit = self._decode_site.get(
                _cc.make_key("verify", donate=donate,
                             mesh=self._mesh_key()),
                self._build_verify,
                stable_key=self._aot_key("verify"),
                example_args=operands, topology=self._topology())
            self._inc("decode_compiles")
        finished = []
        t0 = time.perf_counter()
        with timeline.span("serving.decode_step",
                           active=int(self._active.sum()), paged=True,
                           spec=self._spec_mode_val):
            out = self._decode_jit(*operands)
        self._set_cache(out[:self._n_cache])
        # ptl: disable-next=PTL004 -- capture_logits debug mode readback
        logits_np = (np.asarray(out[self._n_cache + 2])
                     if self.capture_logits else None)
        self._inc("decode_steps")
        self._count_quant_matmuls()
        # committed-token readback: THE designed device->host sync of
        # the speculative decode loop (same role as the non-spec
        # engine's sampled-token fetch, amortized over the whole window)
        # ptl: disable-next=PTL004 -- committed-token readback
        out_np = np.asarray(out[self._n_cache])
        # ptl: disable-next=PTL004 -- committed-count readback
        ncom_np = np.asarray(out[self._n_cache + 1])
        committed, rows = 0, 0
        for s in range(self.slots):
            if not self._active[s]:
                continue
            req = self._slot_req[s]
            nc = int(ncom_np[s])
            rows += 1
            toks_row = [int(t) for t in out_np[s, :nc]]
            self._lens[s] += nc
            self._append_tokens(req, toks_row,
                                logits_np[s] if logits_np is not None
                                else None)
            self._last_tok[s] = toks_row[-1]
            committed += nc
            self._inc("drafted_tokens", k)
            self._inc("accepted_tokens", nc - 1)
            self._inc("rejected_tokens", k - (nc - 1))
            if self._spec_mode_val == "draft" and not req.done:
                req.pending_draft = toks_row
            if req.done:
                finished.append(req)
        self._inc("spec_steps")
        if not self._warming:
            self._commit_sum += committed
            self._rowstep_sum += rows
            if self._rowstep_sum:
                self._g_accept.set(round(
                    self._commit_sum / self._rowstep_sum, 4))
        dt = time.perf_counter() - t0
        if not self._warming:
            self._h_decode.observe(dt)
        self._g_occ.set(int(self._active.sum()))
        self._update_tps()
        if not self._warming and timeline.telemetry_dir():
            timeline.emit({"event": "serving_step",
                           "active": int(self._active.sum()),
                           "queue": len(self._queue),
                           "decode_s": round(dt, 6),
                           "finished": len(finished),
                           "pages_in_use": self._pager.pages_in_use(),
                           "finished_ids": [str(r.id) for r in finished],
                           # per-process total order + emitter (ISSUE 19)
                           "seq": tracing.seq(),
                           "engine": self._engine_id,
                           "replica": self._replica,
                           "spec_mode": self._spec_mode_val,
                           "drafted": k * rows,
                           "accepted": committed - rows,
                           "committed": committed,
                           "accepted_tokens_per_step": round(
                               committed / max(1, rows), 4)})
        if tracing.enabled() and not self._warming:
            for r in finished:
                tracing.event("decode_iter", trace_id=r.trace_id,
                              request_id=r.id, iters=len(r.tokens),
                              decode_s=round(dt, 6), drafted=k * rows,
                              accepted=committed - rows,
                              accepted_tokens_per_step=round(
                                  committed / max(1, rows), 4),
                              engine=self._engine_id)

    # --------------------------------------------------------------- views
    def accepted_tokens_per_step(self):
        """Committed tokens per (row, verify) over live traffic — the
        speedup factor vs one-token decode (1.0 means speculation never
        helped; the bench demands > 1.5 on repetitive traffic)."""
        if not self._rowstep_sum:
            return 0.0
        return round(self._commit_sum / self._rowstep_sum, 4)

    def stats(self):
        out = super().stats()
        out["spec_k"] = self.spec_k
        out["accepted_tokens_per_step"] = self.accepted_tokens_per_step()
        return out
