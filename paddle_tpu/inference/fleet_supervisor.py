"""Supervised router entry point (ISSUE 18): run the
:class:`~paddle_tpu.inference.fleet.ServingFleet` router as a
SUBPROCESS under the PR-3 launch hooks, so the control plane is as
killable as any replica.

Three pieces, all stdlib-only (the router process never imports jax):

* :func:`router_main` — the subprocess body.  Builds a fleet from env
  (``PADDLE_FLEET_MODEL`` spec, ``PADDLE_FLEET_JOURNAL_DIR``,
  ``PADDLE_FLEET_ROLES``/``PADDLE_FLEET_REPLICAS``) and serves a tiny
  length-prefixed JSON control RPC on ``PADDLE_FLEET_CONTROL_PORT``
  (ops: ``submit`` / ``poll`` / ``stats`` / ``kill_replica`` /
  ``shutdown``).  Submits dedupe on request id, so a client retrying
  across a router death is idempotent.

* :func:`supervise_router` — the supervision loop (reuses
  ``distributed/launch.py``'s spawn/incident/backoff hooks): relaunch
  the router on any non-zero exit against the SAME env — same journal
  dir, same control port.  Workers are children of router generation 1;
  a SIGKILL orphans them ALIVE, and the relaunched router re-adopts
  them through the journal + their readopt re-hellos.

* :class:`FleetClient` — the caller side: reconnect-retry RPC wrapper
  that rides through a router death (connection refused/reset while the
  supervisor relaunches) without surfacing an error to the caller.

bench.py's ``routerchaos`` phase drives exactly this triangle: submit
traffic through a FleetClient, SIGKILL the router pid mid-stream, and
assert zero admitted requests lost + token parity + warm re-adoption.
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import socket
import sys
import time

from ..observability import tracing
from .fleet import FleetOverloaded, ServingFleet, recv_msg, send_msg

# spelled out through importlib: paddle_tpu.distributed exports a
# launch() FUNCTION that shadows the submodule attribute
_launch = importlib.import_module("paddle_tpu.distributed.launch")

ROUTER_ARGV = ["-m", "paddle_tpu.inference.fleet_supervisor", "--router"]


# --------------------------------------------------------------- router
def _fleet_from_env():
    spec = json.loads(os.environ.get("PADDLE_FLEET_MODEL") or "{}")
    if not spec:
        raise SystemExit("fleet_supervisor: no PADDLE_FLEET_MODEL spec")
    roles_raw = os.environ.get("PADDLE_FLEET_ROLES")
    return ServingFleet(
        spec,
        roles=json.loads(roles_raw) if roles_raw else None,
        journal_dir=os.environ.get("PADDLE_FLEET_JOURNAL_DIR") or None,
        log_dir=os.environ.get("PADDLE_FLEET_LOG_DIR") or None)


def _op_submit(fleet, msg):
    accepted, rejected = [], []
    for item in msg.get("requests") or []:
        try:
            fleet.submit(item["prompt"],
                         item.get("max_new_tokens", 16),
                         eos_token=item.get("eos_token"),
                         request_id=item["id"],
                         deadline_s=item.get("deadline_s"),
                         priority=item.get("priority", "interactive"))
            accepted.append(item["id"])
        except FleetOverloaded as e:
            rejected.append({"id": item["id"], "err": str(e),
                             "permanent": False})
        except Exception as e:                             # noqa: BLE001
            rejected.append({"id": item["id"],
                             "err": f"{type(e).__name__}: {e}",
                             "permanent": True})
    return {"accepted": accepted, "rejected": rejected}


def _op_poll(fleet):
    done, failed, pending = fleet.results()
    return {"done": done, "failed": failed, "pending": pending,
            "pid": os.getpid(), "replica_pids": fleet.replica_pids(),
            "replica_compiles": fleet.replica_compile_counts(),
            "stats": fleet.stats()}


def router_main():
    """The router subprocess: fleet + control RPC until ``shutdown``.
    Exit 0 is the ONLY clean exit — anything else (SIGKILL above all)
    makes :func:`supervise_router` relaunch against the same journal."""
    port = int(os.environ.get("PADDLE_FLEET_CONTROL_PORT", "0") or 0)
    if not port:
        raise SystemExit(
            "fleet_supervisor: no PADDLE_FLEET_CONTROL_PORT")
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    # the relaunched generation must rebind the SAME port while the
    # dead one's sockets sit in TIME_WAIT
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", port))
    srv.listen(4)
    fleet = _fleet_from_env()
    print(f"# fleet_supervisor: router pid={os.getpid()} serving "
          f"control rpc on 127.0.0.1:{port} "
          f"(journal={fleet.journal_dir or 'off'})", flush=True)
    try:
        while True:
            conn, _ = srv.accept()
            try:
                while True:
                    msg = recv_msg(conn)
                    op = str(msg.get("op", ""))
                    resp = {"ok": True, "seq": msg.get("seq")}
                    if op == "submit":
                        resp.update(_op_submit(fleet, msg))
                    elif op in ("poll", "stats"):
                        resp.update(_op_poll(fleet))
                    elif op == "kill_replica":
                        fleet.kill_replica(int(msg["rid"]))
                    elif op == "shutdown":
                        try:
                            send_msg(conn, resp)
                        except OSError:
                            pass
                        return 0
                    else:
                        resp.update(ok=False, err=f"unknown op {op!r}")
                    send_msg(conn, resp)
            except (OSError, ValueError, ConnectionError):
                pass               # client went away: await the next one
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
    finally:
        fleet.close()
        srv.close()


# ----------------------------------------------------------- supervisor
def supervise_router(env=None, max_restarts=8, backoff=0.5,
                     log_dir=None, stop_event=None):
    """Spawn-and-relaunch loop for the router subprocess.  Returns the
    incident list once the router exits 0 (client-requested shutdown)
    or ``stop_event`` fires; raises after ``max_restarts`` consecutive
    relaunches (a crash-looping CONTROL PLANE is a config error, not
    weather).  Every generation gets the identical env: same journal
    dir, same control port, same model spec — re-adoption depends on
    it."""
    env = dict(env if env is not None else os.environ)
    incidents = []
    incarnation = 0
    t0 = time.time()
    while True:
        env["PADDLE_RESTART_COUNT"] = str(incarnation)
        log_path = (os.path.join(log_dir, f"router-{incarnation}.log")
                    if log_dir else None)
        worker = _launch.spawn_worker(ROUTER_ARGV, env,
                                      log_path=log_path)
        proc = worker["proc"]
        while proc.poll() is None:
            if stop_event is not None and stop_event.is_set():
                _launch.stop_worker(worker)
                _launch.close_worker_log(worker)
                return incidents
            time.sleep(0.1)
        rc = proc.poll()
        _launch.close_worker_log(worker)
        if rc == 0:
            return incidents
        rec = _launch.incident_record("router", rc, incarnation,
                                      log_path=worker.get("log_path"),
                                      t0=t0)
        rec["role"] = "router"
        incidents.append(rec)
        # incident trail (ISSUE 19): the supervisor is the only witness
        # to a SIGKILLed router, so IT files the death event + flight
        # dump; the relaunched router's journal replay files the
        # companion "router_recovery" dump naming the in-flight ids
        tracing.event("router_death", rc=rc,
                      signal=_launch.signal_name(rc),
                      incarnation=incarnation)
        tracing.dump("router_kill",
                     extra={"rc": rc, "incarnation": incarnation,
                            "signal": _launch.signal_name(rc),
                            "log": worker.get("log_path")})
        print(f"# fleet_supervisor: router died rc={rc} "
              f"({_launch.signal_name(rc)}), incarnation "
              f"{incarnation} -> relaunching against the same journal",
              file=sys.stderr, flush=True)
        if incarnation >= max_restarts:
            raise RuntimeError(
                f"router crash-looped past max_restarts="
                f"{max_restarts}: {rec}")
        time.sleep(_launch.backoff_delay(backoff, incarnation,
                                         cap=10.0))
        incarnation += 1


# --------------------------------------------------------------- client
class FleetClient:
    """Reconnect-retry client for the router control RPC.  Every call
    retries through connection refused/reset for ``retry_window_s`` —
    long enough for the supervisor's backoff + the relaunched router's
    journal replay.  Submits are idempotent (ids dedupe server-side),
    so blind retry is safe."""

    def __init__(self, port, host="127.0.0.1", retry_window_s=120.0):
        self.host, self.port = host, int(port)
        self.retry_window_s = float(retry_window_s)
        self._sock = None
        self._seq = 0

    def _close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _rpc(self, msg, retry=True):
        deadline = time.monotonic() + self.retry_window_s
        msg = dict(msg)
        self._seq += 1
        msg["seq"] = self._seq
        while True:
            try:
                if self._sock is None:
                    self._sock = socket.create_connection(
                        (self.host, self.port), timeout=5)
                    self._sock.settimeout(30)
                send_msg(self._sock, msg)
                return recv_msg(self._sock)
            except (OSError, ValueError, ConnectionError):
                self._close()
                if not retry or time.monotonic() > deadline:
                    raise
                time.sleep(0.2)

    def submit(self, requests):
        return self._rpc({"op": "submit", "requests": list(requests)})

    def poll(self):
        return self._rpc({"op": "poll"})

    def stats(self):
        return self._rpc({"op": "stats"})

    def kill_replica(self, rid):
        return self._rpc({"op": "kill_replica", "rid": int(rid)})

    def shutdown(self):
        try:
            return self._rpc({"op": "shutdown"}, retry=False)
        except (OSError, ValueError, ConnectionError):
            return {"ok": True}    # died mid-goodbye: already down
        finally:
            self._close()

    def close(self):
        self._close()


def main(argv=None):
    ap = argparse.ArgumentParser("paddle_tpu.inference.fleet_supervisor")
    ap.add_argument("--router", action="store_true",
                    help="run the router subprocess body (supervisor "
                         "internal; env-driven)")
    args = ap.parse_args(argv)
    if args.router:
        return router_main()
    ap.error("--router is the only entry (the supervision loop is "
             "library API: supervise_router)")


if __name__ == "__main__":
    sys.exit(main())
