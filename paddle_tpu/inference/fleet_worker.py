"""One serving-fleet replica: a subprocess wrapping a
:class:`~paddle_tpu.inference.serving.ServingEngine`, driven by the
router (``inference/fleet.py``) over a length-prefixed JSON RPC on a
loopback socket.

Boot sequence: build the model from the ``PADDLE_FLEET_MODEL`` spec
(every replica builds the IDENTICAL seeded model — that determinism is
what makes router re-queues token-exact), ``warmup()`` the engine
(with a shared ``PADDLE_JIT_CACHE_DIR`` a relaunched replica's warmup is
pure persistent-cache reload: zero compiles), connect to
``PADDLE_FLEET_PORT`` and send the hello carrying warmup/compile/cache
stats.  Then serve RPCs single-threaded — the router owns scheduling.

Delivery contract: finished requests stay in a worker-side buffer and
are re-sent in EVERY step/ping reply until the router acks their ids
(at-least-once; the router dedupes on request id), so a reply lost to a
dropped connection can never lose a completion.  A mid-step engine
failure (device error, injected ``engine_error``) does NOT kill the
replica: the engine's abort path frees the slots and the victims'
ids ride back as ``requeue`` — the router re-queues them elsewhere.

Fault hooks (testing/faults.py): ``replica_kill`` fires inside the
engine's step/admission; ``rpc_delay``/``rpc_drop`` fire per incoming
RPC here (a drop closes the connection without replying, which the
router sees as a vanished replica).

Spec keys (all optional): ``preset`` ("gpt_tiny", default), ``cfg``
(GPTConfig kwargs — overrides preset), ``seed`` (params PRNG, default
0), ``slots``, ``max_len``, ``seq_buckets``, ``batch_buckets``,
``max_queue``, ``warmup`` (default true), ``quant`` (weight-only
quantization mode — "int8"/"int8_dynamic"/"fp8").  With ``paged: true``
the replica runs a
:class:`~paddle_tpu.inference.serving.PagedServingEngine`
(knobs ``page_size``, ``num_pages``, ``prefix_cache``,
``prefill_chunk``, ``kv_dtype`` — "int8" for the quantized page pool)
and its step replies carry the free-page numbers the router's
page-aware least-loaded routing keys on.  With ``spec_mode``
("draft"/"ngram", paged only) the replica runs a
:class:`~paddle_tpu.inference.speculative.SpeculativeServingEngine`
(knobs ``spec_k``, ``spec_draft_cfg``, ``spec_draft_seed``,
``spec_ngram_max``).  The hello's stats echo
``quant``/``kv_dtype``/``spec_mode`` back; the router refuses a replica
whose numeric/behavior contract differs from the fleet spec (a mixed
fp32/int8 fleet must never re-queue a request onto a replica with
different numerics, and a mixed spec/non-spec fleet would skew the
latency/compile attestations the bench reads).
"""
from __future__ import annotations

import argparse
import base64
import json
import os
import socket
import sys
import time

from ..observability import metrics, timeline, tracing
from ..testing import faults as _faults
from .fleet import recv_msg, send_msg


class _HandoffDropped(RuntimeError):
    """Injected ``handoff_drop``: the decode-phase submission is
    refused WITHOUT admitting it — the router must re-ship the pages
    (zero-lost through a dropped handoff)."""


def _encode_kv_payload(arrays):
    """The engine's extracted page arrays as a JSON-able wire dict
    (base64 bytes + shape + dtype per pool operand)."""
    return {"arrays": [
        {"shape": list(a.shape), "dtype": str(a.dtype),
         "data": base64.b64encode(a.tobytes()).decode("ascii")}
        for a in arrays]}


def _decode_kv_payload(item):
    """Inverse of :func:`_encode_kv_payload`: (first_token, arrays)."""
    import numpy as np
    kv = item.get("kv") or {}
    arrays = [np.frombuffer(base64.b64decode(d["data"]),
                            dtype=np.dtype(d["dtype"]))
              .reshape([int(s) for s in d["shape"]])
              for d in kv.get("arrays") or []]
    return int(item["first_token"]), arrays


def _build_engine(spec, role="unified"):
    """The replica's engine, from the router's JSON spec.  Imports jax /
    the GPT stack HERE (worker process), never in the router."""
    import jax
    from ..models import gpt as G
    from .serving import PagedServingEngine, ServingEngine

    preset = spec.get("preset", "gpt_tiny")
    if spec.get("cfg"):
        cfg = G.GPTConfig(**spec["cfg"])
    elif preset == "gpt_tiny":
        cfg = G.gpt_tiny()
    else:
        cfg = getattr(G, preset)()
    if spec.get("params_npz"):
        # checkpoint boot: pure device_put, no RNG executables — the
        # AOT cold-start path's zero-compile contract depends on it
        params = G.load_params_npz(str(spec["params_npz"]))
    else:
        params = G.init_params(cfg,
                               jax.random.PRNGKey(int(spec.get("seed",
                                                               0))))
    kw = {}
    for k in ("slots", "max_len", "max_queue"):
        if spec.get(k) is not None:
            kw[k] = int(spec[k])
    for k in ("seq_buckets", "batch_buckets"):
        if spec.get(k) is not None:
            kw[k] = tuple(int(x) for x in spec[k])
    # the numeric contract (ISSUE 9): quant mode travels in the spec so
    # every replica — and every RELAUNCHED replica — builds the same
    # quantized executables; the hello carries it back for the router's
    # attestation
    if spec.get("quant") is not None:
        kw["quant"] = str(spec["quant"])
    if spec.get("kv_dtype") is not None and not spec.get("paged"):
        # never build an engine that can't honor the spec's numeric
        # contract — the router validates too, but a hand-rolled env
        # must fail loudly here rather than echo kv_dtype=None forever
        raise ValueError(
            "spec has kv_dtype but not paged: true — only the paged "
            "engine has a quantizable KV pool")
    if spec.get("spec_mode") is not None and not spec.get("paged"):
        # same fail-loudly contract as kv_dtype: speculation runs over
        # the paged engine's deferred-commit machinery only
        raise ValueError(
            "spec has spec_mode but not paged: true — speculative "
            "decoding runs over the paged engine")
    if role not in ("unified", "prefill", "decode"):
        raise ValueError(f"unknown replica role {role!r}")
    if role != "unified" and not spec.get("paged"):
        # disaggregation ships KV pages; only the paged engine has them
        raise ValueError(
            f"role {role!r} needs paged: true — disaggregation ships "
            "KV pages")
    if spec.get("tp") is not None:
        # tensor-parallel serving (ISSUE 15): the degree travels in the
        # spec so every (re)launched replica shards identically; the
        # hello's stats echo it back for the contract attestation
        kw["tp"] = int(spec["tp"])
    if spec.get("pp") is not None:
        # pipeline-stage serving (ISSUE 20): same travel-in-the-spec /
        # echo-in-the-hello contract as tp — a mixed-pp fleet refuses
        # at hello (different stage decomposition, different partial-
        # sum order)
        kw["pp"] = int(spec["pp"])
    cls = ServingEngine
    if spec.get("paged"):
        cls = PagedServingEngine
        for k in ("page_size", "num_pages", "prefill_chunk"):
            if spec.get(k) is not None:
                kw[k] = int(spec[k])
        if spec.get("prefix_cache") is not None:
            kw["prefix_cache"] = bool(spec["prefix_cache"])
        if spec.get("kv_dtype") is not None:
            kw["kv_dtype"] = str(spec["kv_dtype"])
        if role != "unified" or spec.get("kv_handoff"):
            # prime the extract/inject executables at warmup — a
            # disaggregated replica's first handoff must not compile.
            # Unified fleets opt in via the spec (ISSUE 17 hot-prefix
            # migration rides the same executables)
            kw["kv_handoff"] = True
        if spec.get("host_tier_mb") is not None:
            # host-RAM page tier (ISSUE 17): evicted device pages spill
            # to a pinned-host LRU and fault back through inject
            kw["host_tier_mb"] = float(spec["host_tier_mb"])
        if spec.get("spec_mode") is not None:
            # speculative decoding (ISSUE 13): the mode travels in the
            # spec so every (re)launched replica speculates identically
            # and the hello's stats echo it back for the router's
            # behavior-contract attestation
            from .speculative import SpeculativeServingEngine
            cls = SpeculativeServingEngine
            kw["spec_mode"] = str(spec["spec_mode"])
            for k in ("spec_k", "spec_draft_seed", "spec_ngram_max"):
                if spec.get(k) is not None:
                    kw[k] = int(spec[k])
            if spec.get("spec_draft_cfg") is not None:
                kw["spec_draft_cfg"] = dict(spec["spec_draft_cfg"])
    return cls((params, cfg), **kw)


def _cache_counters():
    return {"hits": metrics.counter("compile.persistent_cache_hits").value,
            "misses":
                metrics.counter("compile.persistent_cache_misses").value,
            "requests":
                metrics.counter("compile.persistent_cache_requests").value}


def _compile_counters():
    """The replica's compile-layer attestation: backend compiles
    actually run (compile.count, via the timeline hook installed before
    the engine builds) and the AOT artifact traffic.  An artifact-warm
    replica reports xla_compiles == 0 — the fleet cold-start contract
    bench.py asserts."""
    from ..framework.compile_cache import compile_stats
    cs = compile_stats()
    return {"xla_compiles": int(cs.get("count", 0)),
            "aot": {k: cs.get(f"aot_{k}", 0)
                    for k in ("hits", "misses", "saves", "errors")}}


def _stats(engine, extra=None):
    st = engine.stats()
    st["slots"] = engine.slots
    st["persistent_cache"] = _cache_counters()
    st.update(_compile_counters())
    if extra:
        st.update(extra)
    return st


class _Publisher:
    """Time-gated per-replica telemetry snapshot (rank = replica id via
    PADDLE_TRAINER_ID) so the router/bench can merge_from_dir a
    per-replica view through the PR-4 aggregator."""

    def __init__(self):
        try:
            self.interval = float(
                os.environ.get("PADDLE_TELEMETRY_INTERVAL", "2"))
        except ValueError:
            self.interval = 2.0
        self._next = 0.0

    def maybe(self, step=None):
        if not timeline.telemetry_dir():
            return
        now = time.monotonic()
        if now < self._next:
            return
        self._next = now + self.interval
        try:
            from ..observability import aggregate
            aggregate.publish(step=step)
        except Exception:                                  # noqa: BLE001
            pass


def serve(sock, engine, replica, incarnation, role="unified",
          finished=None):
    """The single-threaded RPC loop.  Returns 0 on shutdown / injected
    rpc_drop, or the string ``"gone"`` when the router side of the
    connection vanished — the caller decides whether that means exit
    (unjournaled fleet) or a bounded reconnect-and-readopt wait
    (journaled fleet, ISSUE 18).  ``finished`` is the un-acked
    completion buffer; the caller owns it so a backlog survives the
    reconnect and re-sends to the relaunched router (at-least-once,
    deduped by id)."""
    finished = {} if finished is None else finished
    publisher = _Publisher()
    role_extra = {"role": role}
    while True:
        try:
            msg = recv_msg(sock)
        except (ConnectionError, OSError):
            return "gone"                  # router went away
        op = str(msg.get("op", ""))
        if tracing.enabled() and msg.get("ts") is not None:
            # the receive half of the clock-skew pair: this replica's
            # clock reading of the router's send stamp bounds the
            # assembler's per-process offset from below
            tracing.event("rpc_recv", peer_sent=msg["ts"], op=op)
        if _faults.active() and _faults.rpc_entry(op):
            # rpc_drop: vanish without replying — the router must treat
            # us as unhealthy and re-deliver elsewhere
            print(f"# faults: dropping rpc '{op}' reply",
                  file=sys.stderr, flush=True)
            sock.close()
            return 0
        for rid in msg.get("ack") or []:
            finished.pop(rid, None)
        resp = {"ok": True, "seq": msg.get("seq")}
        if op == "submit":
            from .serving import Request, ServingQueueFull
            accepted, rejected = [], []
            for item in msg.get("requests") or []:
                try:
                    req = Request(item["prompt"],
                                  item.get("max_new_tokens", 16),
                                  eos_token=item.get("eos_token"),
                                  request_id=item["id"])
                    # the router's trace id rides every dispatch: the
                    # engine's span events (queue_wait, prefill_chunk,
                    # extract, inject, decode, completion) stitch into
                    # the same lifecycle
                    req.trace_id = item.get("trace")
                    phase = item.get("phase")
                    if phase == "decode":
                        # the disaggregation handoff: the router ships
                        # the prefill pool's finished pages with the
                        # request — inject instead of re-prefilling
                        if _faults.active() and _faults.handoff_drop():
                            raise _HandoffDropped(
                                "injected handoff_drop: payload "
                                "refused, router must re-ship")
                        tok, arrays = _decode_kv_payload(item)
                        engine.submit_prefilled(req, tok, arrays)
                    else:
                        if phase == "prefill":
                            req.prefill_only = True
                        engine.submit(req)
                    accepted.append(item["id"])
                except (ServingQueueFull, _HandoffDropped) as e:
                    rejected.append({"id": item["id"], "err": str(e),
                                     "permanent": False})
                except Exception as e:                     # noqa: BLE001
                    rejected.append({"id": item["id"],
                                     "err": f"{type(e).__name__}: {e}",
                                     "permanent": True})
            resp.update(accepted=accepted, rejected=rejected)
        elif op in ("step", "ping"):
            requeue, err = [], None

            def buffer_finished(reqs):
                for r in reqs:
                    if r.finish_reason == "prefill_done":
                        # a prefill-phase completion: the handoff
                        # record — first token + the prompt's KV pages
                        # — rides the finished buffer (at-least-once,
                        # acked and deduped by id like any completion)
                        finished[str(r.id)] = {
                            "id": str(r.id), "phase": "prefill",
                            "first_token": int(r.tokens[0]),
                            "kv_bytes": int(sum(
                                a.nbytes for a in r.kv_payload)),
                            "kv": _encode_kv_payload(r.kv_payload)}
                        r.kv_payload = None     # the record owns it now
                        continue
                    finished[str(r.id)] = {
                        "id": str(r.id),
                        "tokens": [int(t) for t in r.tokens],
                        "finish_reason": r.finish_reason}
            for _ in range(max(int(msg.get("max_steps", 1)), 0)):
                st = engine.stats()
                if not (st["queue_depth"] or st["slot_occupancy"]):
                    break
                try:
                    buffer_finished(engine.step())
                except Exception as e:                     # noqa: BLE001
                    # slot-leak fix at work: the engine freed every slot
                    # and parked the victims — hand their ids back for
                    # router-side re-queueing and KEEP SERVING.  Anything
                    # that COMPLETED before the failure is still on the
                    # engine's finished backlog: report it, don't re-run
                    err = f"{type(e).__name__}: {e}"
                    requeue = [str(r.id)
                               for r in engine.take_aborted()]
                    buffer_finished(engine.take_finished())
                    break
            resp.update(finished=list(finished.values()),
                        requeue=requeue, error=err)
        elif op == "cancel":
            cancelled = [rid for rid in msg.get("ids") or []
                         if engine.cancel(rid) is not None]
            resp.update(cancelled=cancelled)
        elif op == "shutdown":
            try:
                send_msg(sock, resp)
            except OSError:
                pass
            return 0
        else:
            resp.update(ok=False, err=f"unknown op {op!r}")
        resp["stats"] = _stats(engine, dict(
            role_extra, replica=replica, incarnation=incarnation,
            pid=os.getpid()))
        # cancels ride every message, not just "cancel" ops
        for rid in msg.get("cancel") or []:
            engine.cancel(rid)
        if tracing.enabled():
            # the reply half of the skew pair (bounds the offset from
            # above) + the pid trace assembly groups this clock under
            resp["ts"] = tracing.now()
            resp["pid"] = os.getpid()
        try:
            send_msg(sock, resp)
        except OSError:
            return "gone"
        publisher.maybe(step=engine.stats()["decode_steps"])


def _await_new_router(host, port):
    """The router vanished mid-conversation.  A journaled fleet sets
    ``PADDLE_FLEET_READOPT_TIMEOUT_S`` in every worker's env: keep the
    engine (and its in-flight work) ALIVE and retry the router port for
    that window — the relaunched router rebinds the journaled port and
    this worker re-hellos with a readopt claim.  Unset/zero (no
    journal) preserves the old contract exactly: exit clean, the router
    relaunches a fresh replica.  Returns a connected socket or None."""
    try:
        window = float(
            os.environ.get("PADDLE_FLEET_READOPT_TIMEOUT_S", "0"))
    except ValueError:
        window = 0.0
    if window <= 0:
        return None
    if _faults.active() and _faults.readopt_refused():
        # injected readopt_timeout: this worker never comes back — the
        # router's recovery window must expire and re-queue its work
        print("# faults: readopt refused, exiting instead of "
              "reconnecting", file=sys.stderr, flush=True)
        return None
    deadline = time.monotonic() + window
    print(f"# fleet_worker: router connection lost, retrying "
          f"{host}:{port} for {window:.0f}s", file=sys.stderr,
          flush=True)
    while time.monotonic() < deadline:
        try:
            sock = socket.create_connection((host, port), timeout=2)
            sock.settimeout(None)
            return sock
        except OSError:
            time.sleep(0.2)
    return None


def _readopt_hello(sock, engine, replica, incarnation, role):
    """The surviving worker's RE-hello: same attestations as a boot
    hello (the relaunched router re-checks the numeric contract) plus
    ``readopt`` and the in-flight id claims."""
    claims = engine.active_request_ids()
    send_msg(sock, {"op": "hello", "readopt": True,
                    "replica": replica, "pid": os.getpid(),
                    "incarnation": incarnation,
                    "inflight": claims,
                    "persistent_cache": _cache_counters(),
                    "compile": _compile_counters(),
                    "stats": _stats(engine, {"role": role})})
    tracing.event("readopt_hello", replica=replica,
                  incarnation=incarnation, claims=len(claims))


def main(argv=None):
    ap = argparse.ArgumentParser("paddle_tpu.inference.fleet_worker")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("PADDLE_FLEET_PORT", 0)))
    ap.add_argument("--replica", type=int,
                    default=int(os.environ.get("PADDLE_FLEET_REPLICA",
                                               0)))
    args = ap.parse_args(argv)
    if not args.port:
        ap.error("no router port (--port / PADDLE_FLEET_PORT)")
    incarnation = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
    spec = json.loads(os.environ.get("PADDLE_FLEET_MODEL") or "{}")
    # the disaggregation role is PER-REPLICA (the router assigns it via
    # env); the spec-level key is the single-process fallback
    role = (os.environ.get("PADDLE_FLEET_ROLE")
            or spec.get("role") or "unified")

    # replica_slow_start fault: a deterministically slow joiner — the
    # elastic router/autoscaler must tolerate a scale-up replica whose
    # hello is late without wedging or counting phantom capacity
    _faults.slow_start_check()

    t0 = time.perf_counter()
    tracing.set_role("replica", args.replica)
    # the compile hook must be live BEFORE the engine builds so the
    # hello's xla_compiles attestation covers every boot compile
    timeline.install_compile_hook()
    engine = _build_engine(spec, role)
    warm = engine.warmup() if spec.get("warmup", True) else 0
    boot_s = time.perf_counter() - t0

    sock = socket.create_connection((args.host, args.port), timeout=30)
    sock.settimeout(None)              # the router owns the cadence
    send_msg(sock, {"op": "hello", "replica": args.replica,
                    "pid": os.getpid(), "incarnation": incarnation,
                    "warmup_prefill_compiles": warm,
                    "boot_s": round(boot_s, 3),
                    "persistent_cache": _cache_counters(),
                    "compile": _compile_counters(),
                    "stats": _stats(engine, {"role": role})})
    timeline.emit({"event": "fleet_replica_up", "replica": args.replica,
                   "incarnation": incarnation, "role": role,
                   "boot_s": round(boot_s, 3),
                   "warmup_prefill_compiles": warm,
                   "persistent_cache": _cache_counters(),
                   "compile": _compile_counters()})
    finished = {}          # un-acked completions, ACROSS reconnects
    while True:
        rc = serve(sock, engine, args.replica, incarnation, role,
                   finished=finished)
        if rc != "gone":
            return rc
        try:
            sock.close()
        except OSError:
            pass
        while True:
            sock = _await_new_router(args.host, args.port)
            if sock is None:
                return 0               # no journaled router coming back
            try:
                _readopt_hello(sock, engine, args.replica, incarnation,
                               role)
                break
            except OSError as e:
                # the connect can land an instant before the relaunched
                # router dies too, or race its teardown RST: one failed
                # hello must not burn the whole window — back into a
                # fresh reconnect wait
                print(f"# fleet_worker: readopt hello failed ({e}), "
                      "retrying", file=sys.stderr, flush=True)
                try:
                    sock.close()
                except OSError:
                    pass
        timeline.emit({"event": "fleet_replica_readopt",
                       "replica": args.replica,
                       "incarnation": incarnation, "role": role,
                       "inflight": len(engine.active_request_ids())})


if __name__ == "__main__":
    sys.exit(main())
