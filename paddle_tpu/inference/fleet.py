"""Fault-tolerant serving fleet (ISSUE 7 tentpole): a front-end router
admitting requests across N supervised :class:`ServingEngine` replicas,
guaranteeing **no admitted request is ever dropped**.

This is the composition layer ROADMAP direction 4 calls for — the PR-3
supervision machinery (incident records, exponential-backoff relaunch,
``distributed/launch.py``'s reusable worker hooks), the PR-4 telemetry
registry, and the PR-5 continuous-batching engine assembled into a
serving *fleet*:

* **replica lifecycle** — each replica is a subprocess
  (``paddle_tpu/inference/fleet_worker.py``) speaking a length-prefixed
  JSON RPC over a loopback socket.  The router spawns it through
  ``launch.spawn_worker`` (log tee per incarnation), health-checks it
  with a per-RPC heartbeat deadline, detects crashes (process exit, EOF,
  heartbeat miss), handles each incident exactly once, and relaunches
  with exponential backoff under a restart budget — with a shared
  ``PADDLE_JIT_CACHE_DIR`` the replacement warm-restarts from the
  persistent compilation cache and compiles nothing.
* **request durability** — every admitted request carries a stable
  client-suppliable id (auto-uuid otherwise) and lives in the router's
  PENDING table until its final token is acked.  Requests in flight on a
  crashed replica are **re-queued** onto survivors (greedy decoding over
  identical replica weights makes the retry token-exact), with bounded
  retries + backoff, per-request deadlines, and completion dedupe on the
  id — a replica double-reporting after a dropped reply can never
  double-complete.
* **graceful degradation** — replicas pull work proportionally to their
  free capacity (the ``serving.*`` occupancy/queue numbers each step
  reply carries), so a slow replica naturally sheds load to fast ones;
  past the global ``max_pending`` bound :meth:`ServingFleet.submit`
  raises :class:`FleetOverloaded` immediately (a fast 429, not unbounded
  latency).
* **elastic lifecycle** (ISSUE 11) — :meth:`ServingFleet.add_replica`
  spawns a fresh supervised replica at runtime (with a shared
  ``PADDLE_JIT_CACHE_DIR`` it joins warm: 0 persistent-cache misses),
  and :meth:`ServingFleet.remove_replica` **drain-then-stops**: the
  replica stops receiving dispatches, finishes (or re-queues, past
  ``PADDLE_FLEET_DRAIN_TIMEOUT_S``) its in-flight work, then exits — so
  the zero-lost guarantee holds through every scale-down.  The
  :mod:`~paddle_tpu.inference.autoscale` control loop drives both from
  the fleet's own telemetry (queue depth, occupancy, p99 vs the
  ``PADDLE_FLEET_SLO_P99_S`` target).
* **priority classes** (ISSUE 11) — ``submit(..., priority="batch")``
  marks sheddable work.  Dispatch is weighted-fair (interactive first,
  but batch never starves), and under overload the shed ALWAYS hits the
  batch class first: an interactive arrival past ``max_pending``
  displaces a queued — then an in-flight — batch request (failed with
  the named reason ``shed_overload``) and is itself shed only when no
  batch work exists anywhere in the fleet.

Telemetry rides the ``fleet.*`` registry family (replica up/down
gauges, requeues, retries, sheds, heartbeat misses, incidents, recovery
seconds) and merges through the PR-4 cross-rank aggregation view — each
replica publishes per-replica snapshots into the shared telemetry dir,
the router publishes its own under rank ``nreplicas``.

The router process never touches the jax backend or builds the model:
construction happens inside the workers (spec via the
``PADDLE_FLEET_MODEL`` env var), so the router stays responsive while
replicas compile, and a wedged XLA client can never take the control
plane down with it.
"""
from __future__ import annotations

import collections
import importlib
import json
import os
import signal
import socket
import struct
import threading
import time
import uuid

import numpy as np

from ..observability import metrics, timeline, tracing
# pure numpy/hashlib helpers (kv_pager never imports jax): the router
# computes the IDENTICAL sticky-routing digest a replica's pager does
from .kv_pager import prompt_chain_keys, short_digest
# write-ahead request journal (ISSUE 18): stdlib-only, like everything
# else the router imports
from . import journal as _journal

# spelled out through importlib: paddle_tpu.distributed exports a
# launch() FUNCTION that shadows the submodule attribute
_launch = importlib.import_module("paddle_tpu.distributed.launch")

__all__ = ["ServingFleet", "FleetRequest", "FleetOverloaded",
           "send_msg", "recv_msg"]

# the router's telemetry-snapshot rank.  A constant far above any
# replica id: elastic fleets mint replica ids monotonically, so the
# historical choice (rank = nreplicas) would collide with the first
# scaled-up replica's id.
ROUTER_RANK = 1000

PRIORITIES = ("interactive", "batch")

# replica roles (ISSUE 15 prefill/decode disaggregation).  A fleet is
# either fully "unified" (every replica prefills AND decodes — the
# historical shape) or fully disaggregated (only "prefill" and "decode"
# replicas, at least one of each); an incoherent mix refuses at
# construction, and a replica whose hello reports a different role than
# assigned refuses at hello like a numeric-contract mismatch.
ROLES = ("unified", "prefill", "decode")


class FleetOverloaded(RuntimeError):
    """submit() load shedding: the router's global pending table is at
    ``max_pending`` — reject fast (the caller retries/sheds) instead of
    queueing into unbounded latency.  The serving-fleet 429."""


# --------------------------------------------------------------------------
# wire protocol: 4-byte big-endian length + UTF-8 JSON (shared with
# fleet_worker.py; stdlib-only so workers can import it before jax)
# --------------------------------------------------------------------------

_LEN = struct.Struct(">I")
MAX_FRAME = 64 << 20          # a malformed peer must not OOM the router


def send_msg(sock, obj):
    data = json.dumps(obj).encode("utf-8")
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed the connection")
        buf += chunk
    return buf


def recv_msg(sock):
    n = _LEN.unpack(_recv_exact(sock, 4))[0]
    if n > MAX_FRAME:
        raise ConnectionError(f"oversized frame ({n} bytes)")
    return json.loads(_recv_exact(sock, n).decode("utf-8"))


# --------------------------------------------------------------------------
# env knobs (MIGRATING.md documents these)
# --------------------------------------------------------------------------

def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return int(default)


def _stats_family():
    return metrics.stats_family("fleet", {
        "requests_admitted": 0, "requests_completed": 0,
        "requests_failed": 0, "requeues": 0, "retries": 0,
        "sheds": 0, "sheds_batch": 0, "sheds_interactive": 0,
        "dup_completions": 0, "heartbeat_misses": 0,
        "incidents": 0, "replica_restarts": 0, "rpc_errors": 0,
        "deadline_exceeded": 0, "rejects_permanent": 0,
        "scale_ups": 0, "scale_downs": 0, "drain_requeues": 0,
        # prefill/decode disaggregation (ISSUE 15): completed prefill
        # phases whose KV pages crossed the router, the bytes that
        # crossed, and payloads SHIPPED MORE THAN ONCE (a decode
        # replica died or dropped the handoff; zero-lost re-ships)
        "kv_handoffs": 0, "kv_handoff_bytes": 0, "handoff_reships": 0,
        # prefix-aware routing + hot-prefix migration (ISSUE 17):
        # dispatches held for their sticky replica, sticky targets that
        # were unusable (dead/draining/full -> least-loaded fallback),
        # and hot chains copied to a cold replica via the handoff path
        "prefix_routed": 0, "prefix_fallbacks": 0,
        "prefix_migrations": 0, "migration_bytes": 0,
        # router crash-restart (ISSUE 18): workers re-adopted by a
        # restarted router, journaled requests re-queued at replay,
        # parked handoffs lost with the old router's memory (recovery
        # re-prefills them via the PR-17 fault-back path), and ids
        # that could NOT be recovered (failed named router_recovery)
        "readopts": 0, "router_recoveries": 0,
        "recovery_requeues": 0, "recovery_rehandoffs": 0,
        "recovery_failures": 0})


def fleet_stats():
    """The process-global ``fleet.*`` counter family."""
    return dict(_stats_family())


class FleetRequest:
    """One request's router-side lifecycle record — the PENDING-table
    entry that guarantees durability.  ``id`` is stable across retries
    and replicas (client-suppliable, auto-uuid otherwise)."""

    def __init__(self, prompt, max_new_tokens, eos_token=None,
                 request_id=None, deadline_s=None, priority="interactive"):
        self.id = str(request_id) if request_id is not None \
            else uuid.uuid4().hex
        self.prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not self.prompt:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token = eos_token
        self.deadline_s = deadline_s
        if priority not in PRIORITIES:
            raise ValueError(f"priority {priority!r} is unknown — "
                             f"expected one of {PRIORITIES}")
        self.priority = priority
        self.tokens = []
        self.finish_reason = None
        self.done = False
        self.failed = False
        self.error = None
        self.retries = 0              # re-queues consumed so far
        self.replica = None           # current / completing replica
        self.replicas_tried = []
        self.not_before = 0.0         # retry-backoff dispatch gate
        # disaggregation lifecycle (ISSUE 15; all None/0 on unified
        # fleets): phase "prefill" -> (handoff: kv payload + first
        # token land here) -> "decode" -> completion.  The payload
        # LIVES ON THE PENDING-TABLE ENTRY, so a decode replica dying
        # mid-stream re-ships the same pages — retries never lose KV.
        self.phase = None
        self.kv = None                # wire-form page payload
        self.kv_bytes = 0
        self.kv_ships = 0             # decode dispatches carrying kv
        self.first_token = None
        self.prefill_replica = None
        self.decode_t0 = None         # when the decode phase began
        # prefix-aware routing (ISSUE 17): prefix_chain holds the
        # prompt's full-page chain digests DEEPEST-FIRST — the router
        # matches the deepest digest any replica advertises, so exact
        # repeats go to the replica holding the whole chain while fresh
        # prefix-sharers still match the shared head page.  A hot-prefix
        # migration pins the prefill to the chain's current owner and
        # the decode to the replica the router wants the chain copied
        # onto
        self.prefix_chain = ()
        self.prefix_digest = None     # head digest: hotspot accounting
        self.migrate_from = None
        self.migrate_to = None
        self.submit_t = time.perf_counter()
        # wall-clock admission stamp: journaled, so a request replayed
        # by a RESTARTED router keeps its original deadline budget
        # (perf_counter timelines don't survive the process)
        self.admit_wall = time.time()
        self.finish_t = None
        # distributed tracing (ISSUE 19): the router mints trace_id at
        # admission and threads it through every hop; the *_t stamps
        # are tracing-clock phase boundaries (queue/prefill/parked/
        # decode attribution — the autoscaler's dominant_phase signal)
        self.trace_id = None
        self.admit_t = None
        self.dispatch_t = None        # FIRST dispatch (retries keep it)
        self.park_t = None
        self.ship_t = None

    def latency(self):
        return (self.finish_t - self.submit_t) \
            if self.finish_t is not None else None

    def decode_latency(self):
        """Decode-phase seconds (handoff -> completion, decode-pool
        queueing included) on a disaggregated fleet — the latency the
        disagg bench holds flat while prefill load grows.  None before
        completion and on unified fleets."""
        if self.finish_t is None or self.decode_t0 is None:
            return None
        return self.finish_t - self.decode_t0

    def expired(self, now=None):
        if self.deadline_s is None:
            return False
        return (now or time.perf_counter()) - self.submit_t \
            > self.deadline_s


def _pid_alive(pid):
    """Signal-0 liveness probe for an ADOPTED worker pid (a process the
    previous router generation spawned; this router holds no waitable
    handle for it)."""
    if not pid or int(pid) <= 0:
        return False
    try:
        os.kill(int(pid), 0)
    except OSError:
        return False
    return True


def rebuild_request(view, now_wall=None, now_perf=None):
    """One replayed journal view (``JournalState.requests`` value with
    an intact admit record) -> a live :class:`FleetRequest`.

    The admit record's wall-clock stamp maps back onto this process's
    perf_counter timeline, so the rebuilt request keeps its ORIGINAL
    deadline: time burned before the crash stays burned.  A journaled
    decode-phase request comes back with its phase preserved but
    ``kv=None`` — the payload bytes died with the old router's memory;
    reconciliation either lets the claiming decode replica finish it or
    flips it back to the prefill phase (re-extract/re-prefill, the
    PR-17 fault-back shape)."""
    rec = view["rec"]
    req = FleetRequest(rec["prompt"], rec["max_new_tokens"],
                       eos_token=rec.get("eos_token"),
                       request_id=view["id"],
                       deadline_s=rec.get("deadline_s"),
                       priority=rec.get("priority") or "interactive")
    admit_wall = rec.get("admit_wall")
    if admit_wall is not None:
        req.admit_wall = float(admit_wall)
        req.submit_t = _journal.resume_submit_t(
            admit_wall, now_wall=now_wall, now_perf=now_perf)
    req.retries = int(view.get("retries") or 0)
    # the trace id survives the crash with the admit record, so a
    # post-recovery completion stitches into the SAME lifecycle the
    # pre-crash hops started
    req.trace_id = rec.get("trace")
    req.phase = view.get("phase")
    if req.phase == "decode":
        req.first_token = view.get("first_token")
        req.prefill_replica = view.get("prefill_replica")
        req.kv = None
        req.kv_bytes = 0
    return req


class _ReplicaGone(RuntimeError):
    """Internal: this replica just failed (crash/EOF/heartbeat miss) —
    unwind to the driver loop's incident handler."""


class _Replica:
    def __init__(self, rid, listener, role="unified"):
        self.id = rid
        self.role = role
        self.listener = listener           # lives across incarnations
        self.port = listener.getsockname()[1]
        self.worker = None                 # launch.spawn_worker handle
        # a worker the PREVIOUS router generation spawned and this one
        # re-adopted from the journal: liveness via signal 0, stop via
        # os.kill — there is no waitable Popen handle for it
        self.adopted_pid = None
        self.conn = None
        self.state = "starting"    # starting | healthy | dead | removed
        self.incarnation = 0
        self.restarts_used = 0
        self.inflight = {}                 # id -> FleetRequest
        self.last_stats = {}
        self.hello = {}
        self.pending_ack = []              # completion ids to ack next RPC
        self.pending_cancel = []           # ids to cancel next RPC
        self.incident_t = None             # set on incident, cleared on
        self.next_spawn_t = 0.0            # recovery (recovery_s source)
        self.spawn_deadline = None
        self.thread = None                 # this replica's driver thread
        self.draining = False              # scale-down: no new dispatches
        self.drain_t0 = None               # when draining began
        self.scale_ev = None               # open scale-up event record

    @property
    def pid(self):
        if self.worker is not None:
            return self.worker["proc"].pid
        return self.adopted_pid


class ServingFleet:
    """Route requests across ``replicas`` supervised serving workers.

    ``model_spec`` is the worker-side model/engine recipe (a JSON-able
    dict — see fleet_worker.py: ``cfg`` GPTConfig kwargs or ``preset``,
    ``seed``, plus engine knobs ``slots``/``max_len``/``seq_buckets``/
    ``batch_buckets``).  Every replica builds the IDENTICAL model, which
    is what makes re-queued greedy requests token-exact.

    The router guarantees: an admitted request either completes, or
    fails with a named reason (``deadline_exceeded``,
    ``retries_exhausted``, a permanent worker reject, or fleet
    shutdown) — it is never silently dropped.  Call :meth:`drain` to
    wait for the pending table to empty, :meth:`close` to tear down.
    """

    def __init__(self, model_spec, replicas=None, *, env_base=None,
                 log_dir=None, jit_cache_dir=None, aot_cache_dir=None,
                 telemetry_dir=None,
                 heartbeat_s=None, heartbeat_idle_s=0.05,
                 request_deadline_s=None, max_retries=None,
                 retry_backoff_s=None, max_pending=None,
                 max_restarts=None, restart_backoff_s=None,
                 spawn_timeout_s=None, steps_per_rpc=4,
                 dispatch_queue_depth=None, worker_argv=None,
                 drain_timeout_s=None, interactive_weight=None,
                 roles=None, journal_dir=None):
        self.model_spec = dict(model_spec or {})
        # spec keys the built engine could not honor would otherwise
        # surface as a fleet-wide boot crash or hello contract mismatch
        # — a config slip must fail HERE, in the caller's process, not
        # as N permanently-dead replicas.  The mode list mirrors
        # models/gpt.py::QUANT_MODES (importing it would pull jax into
        # the router, which deliberately never loads a backend);
        # fp8 *availability* can only be probed worker-side.
        if (self.model_spec.get("kv_dtype") is not None
                and not self.model_spec.get("paged")):
            raise ValueError(
                "model_spec has kv_dtype but not paged: true — only the "
                "paged engine has a quantizable KV pool")
        quant = self.model_spec.get("quant")
        if quant is not None and quant not in ("int8", "int8_dynamic",
                                               "fp8"):
            raise ValueError(
                f"model_spec quant mode {quant!r} is unknown — expected "
                "one of ('int8', 'int8_dynamic', 'fp8')")
        if self.model_spec.get("kv_dtype") not in (None, "int8"):
            raise ValueError(
                f"model_spec kv_dtype {self.model_spec['kv_dtype']!r} "
                "is unknown — expected 'int8' or omit it")
        if ((self.model_spec.get("kv_handoff")
             or self.model_spec.get("host_tier_mb") is not None)
                and not self.model_spec.get("paged")):
            raise ValueError(
                "model_spec has kv_handoff/host_tier_mb but not "
                "paged: true — KV pages exist only on the paged engine")
        spec_mode = self.model_spec.get("spec_mode")
        if spec_mode is not None and spec_mode not in ("draft", "ngram"):
            raise ValueError(
                f"model_spec spec_mode {spec_mode!r} is unknown — "
                "expected 'draft', 'ngram', or omit it")
        if spec_mode is not None and not self.model_spec.get("paged"):
            raise ValueError(
                "model_spec has spec_mode but not paged: true — "
                "speculative decoding runs over the paged engine")
        if spec_mode is not None:
            # same fail-HERE contract as quant/kv_dtype: a bad spec knob
            # must not surface as N replicas crash-looping through their
            # whole restart budget before the first hello
            spec_k = self.model_spec.get("spec_k")
            if spec_k is not None and (not isinstance(spec_k, int)
                                       or spec_k < 1):
                raise ValueError(
                    f"model_spec spec_k must be an int >= 1, got "
                    f"{spec_k!r}")
            draft_cfg = self.model_spec.get("spec_draft_cfg")
            if draft_cfg is not None and not isinstance(draft_cfg, dict):
                raise ValueError(
                    "model_spec spec_draft_cfg must be a dict of "
                    f"GPTConfig kwargs, got {type(draft_cfg).__name__}")
        tp = self.model_spec.get("tp")
        if tp is not None and (not isinstance(tp, int) or tp < 1):
            raise ValueError(
                f"model_spec tp must be an int >= 1, got {tp!r}")
        pp = self.model_spec.get("pp")
        if pp is not None and (not isinstance(pp, int) or pp < 1):
            raise ValueError(
                f"model_spec pp must be an int >= 1, got {pp!r}")
        if pp is not None and pp > 1 and not self.model_spec.get("paged"):
            raise ValueError(
                "model_spec has pp > 1 but not paged: true — the 1F1B "
                "stage step exists only on the paged engine (same "
                "fail-here contract as spec_mode/kv_handoff)")
        # replica roles (ISSUE 15): None -> all unified; a list of role
        # strings (one per replica) or a {"prefill": n, "decode": m}
        # count dict -> a disaggregated fleet.  Coherence is validated
        # HERE, in the caller's process — an incoherent fleet would
        # strand one phase's requests forever.
        role_plan = self._normalize_roles(roles)
        if role_plan is not None and replicas is not None \
                and len(role_plan) != int(replicas):
            raise ValueError(
                f"roles names {len(role_plan)} replicas but replicas="
                f"{replicas} — drop one or make them agree")
        self.nreplicas = int(
            replicas if replicas is not None
            else (len(role_plan) if role_plan is not None
                  else _env_int("PADDLE_FLEET_REPLICAS", 2)))
        if self.nreplicas < 1:
            raise ValueError("a fleet needs at least one replica")
        if role_plan is None:
            role_plan = ["unified"] * self.nreplicas
        self.disaggregated = any(r != "unified" for r in role_plan)
        if self.disaggregated:
            if "unified" in role_plan:
                raise ValueError(
                    f"role-incoherent fleet {role_plan}: mixing "
                    "unified with prefill/decode replicas splits the "
                    "request stream two incompatible ways — use all "
                    "unified, or prefill+decode only")
            if "prefill" not in role_plan or "decode" not in role_plan:
                raise ValueError(
                    f"a disaggregated fleet needs at least one prefill "
                    f"AND one decode replica, got {role_plan}")
            if not self.model_spec.get("paged"):
                raise ValueError(
                    "disaggregation ships KV pages — the spec needs "
                    "paged: true")
        self._role_plan = role_plan
        self.env_base = dict(env_base if env_base is not None
                             else os.environ)
        self.log_dir = log_dir
        self.jit_cache_dir = jit_cache_dir \
            or self.env_base.get("PADDLE_JIT_CACHE_DIR")
        # AOT artifact dir (ISSUE 14): replicas load serialized
        # executables from here and boot with zero XLA compiles
        self.aot_cache_dir = aot_cache_dir \
            or self.env_base.get("PADDLE_AOT_CACHE_DIR")
        self.telemetry_dir = telemetry_dir \
            or self.env_base.get("PADDLE_TELEMETRY_DIR")
        # distributed tracing (ISSUE 19): the router is the trace root —
        # it mints trace ids and owns the reference clock the assembler
        # skew-corrects replicas against.  Its timeline events move to
        # the utility rank's file (events_rank1000.jsonl) so they never
        # interleave with — or race the rotation of — replica 0's file
        # when both share the telemetry dir.
        tracing.set_role("router")
        if self.telemetry_dir:
            timeline.set_rank_override(ROUTER_RANK)
        self.heartbeat_s = heartbeat_s if heartbeat_s is not None \
            else _env_float("PADDLE_FLEET_HEARTBEAT_S", 10.0)
        self.heartbeat_idle_s = heartbeat_idle_s
        self.request_deadline_s = request_deadline_s \
            if request_deadline_s is not None \
            else (_env_float("PADDLE_FLEET_DEADLINE_S", 0.0) or None)
        self.max_retries = max_retries if max_retries is not None \
            else _env_int("PADDLE_FLEET_MAX_RETRIES", 3)
        self.retry_backoff_s = retry_backoff_s \
            if retry_backoff_s is not None \
            else _env_float("PADDLE_FLEET_RETRY_BACKOFF_S", 0.25)
        self.max_restarts = max_restarts if max_restarts is not None \
            else _env_int("PADDLE_FLEET_MAX_RESTARTS", 8)
        self.restart_backoff_s = restart_backoff_s \
            if restart_backoff_s is not None \
            else _env_float("PADDLE_FLEET_RESTART_BACKOFF_S", 0.5)
        self.spawn_timeout_s = spawn_timeout_s \
            if spawn_timeout_s is not None \
            else _env_float("PADDLE_FLEET_SPAWN_TIMEOUT_S", 180.0)
        self.steps_per_rpc = int(steps_per_rpc)
        slots = int(self.model_spec.get("slots", 4))
        self.dispatch_queue_depth = int(
            dispatch_queue_depth if dispatch_queue_depth is not None
            else slots)
        self._slots = slots
        self.max_pending = int(
            max_pending if max_pending is not None
            else _env_int("PADDLE_FLEET_MAX_PENDING",
                          8 * slots * self.nreplicas))
        self.worker_argv = list(worker_argv) if worker_argv else \
            ["-m", "paddle_tpu.inference.fleet_worker"]
        # scale-down drain bound: past it the still-in-flight requests
        # are re-queued onto survivors (zero-lost holds either way — the
        # bound only caps how long a removal politely waits)
        self.drain_timeout_s = drain_timeout_s \
            if drain_timeout_s is not None \
            else _env_float("PADDLE_FLEET_DRAIN_TIMEOUT_S", 30.0)
        # weighted-fair dispatch: W interactive pops per 1 batch pop when
        # both classes wait — interactive goes first, batch never starves
        self.interactive_weight = int(
            interactive_weight if interactive_weight is not None
            else _env_int("PADDLE_FLEET_INTERACTIVE_WEIGHT", 4))
        # finished-request retention: the _done/_failed tables double as
        # the dedupe window, so they are BOUNDED (oldest evicted) — a
        # sustained-traffic router must not grow without limit
        self.done_retention = _env_int("PADDLE_FLEET_DONE_RETENTION",
                                       4096)
        # write-ahead request journal (ISSUE 18): None keeps the exact
        # historical behavior — no journal, zero overhead.  With a dir,
        # every control-plane event is journaled and a RESTARTED router
        # pointed at the same dir replays the pending table and
        # re-adopts the still-live workers instead of spawning anew.
        self.journal_dir = journal_dir \
            or self.env_base.get("PADDLE_FLEET_JOURNAL_DIR") or None
        self._readopt_timeout_s = _env_float(
            "PADDLE_FLEET_READOPT_TIMEOUT_S", 60.0)
        self._journal = None
        self._recovering = False
        self._recover_t0 = None
        self.router_recovery_s = None
        self._awaiting_readopt = set()
        self.readopt_events = []
        self._g_router_recovery = metrics.gauge(
            "fleet.router_recovery_s")
        # prefix-aware routing (ISSUE 17): replicas roll their pager's
        # chain digests into every step-stats reply; the router indexes
        # digest -> replica and holds prefix-sharing dispatches for the
        # chain's owner (falling back to least-loaded the moment the
        # owner is dead, draining, or out of capacity)
        self._spec_page_size = int(self.model_spec.get("page_size")
                                   or 16)
        self._hash_salt = (
            f"quant={self.model_spec.get('quant') or 'none'}"
            f"/kv={'int8' if self.model_spec.get('kv_dtype') == 'int8' else 'fp'}")
        self.prefix_sticky = (
            bool(_env_int("PADDLE_FLEET_PREFIX_STICKY", 1))
            and bool(self.model_spec.get("paged")))
        self._prefix_index = collections.OrderedDict()  # digest -> rid
        self._route_counts = collections.OrderedDict()  # digest -> [n, t0]
        # hot-prefix migration: past migrate_hot_routes sticky routes
        # to ONE replica inside migrate_window_s, the chain is copied
        # (extract -> park -> inject, the ISSUE-15 machinery) to a cold
        # replica and the index repointed — stickiness never hotspots.
        # Unified fleets only, and only when the spec opted into
        # kv_handoff (otherwise workers never primed inject).
        self.migrate_hot_routes = _env_int(
            "PADDLE_FLEET_MIGRATE_HOT_ROUTES", 8)
        self.migrate_window_s = _env_float(
            "PADDLE_FLEET_MIGRATE_WINDOW_S", 10.0)
        self.migrate_enabled = (
            self.prefix_sticky and self.migrate_hot_routes > 0
            and not self.disaggregated
            and bool(self.model_spec.get("kv_handoff")))

        self._stats = _stats_family()
        # the fleet.* family is process-global; mirror every count into
        # THIS fleet's own dict (stats() reports it) so a coexisting
        # fleet's traffic is never misattributed — same discipline as
        # ServingEngine._inc
        self._counts = {k: 0 for k in self._stats}
        self._g_up = metrics.gauge("fleet.replicas_up")
        self._g_configured = metrics.gauge("fleet.replicas_configured")
        self._g_target = metrics.gauge("fleet.replicas_target")
        self._g_pending = metrics.gauge("fleet.pending")
        self._g_recovery = metrics.gauge("fleet.last_recovery_s")
        self._h_latency = metrics.histogram("fleet.request_latency_s")
        # instance-local latency window: the registry histogram pools
        # every fleet in the process, so stats() percentiles come from
        # here (same cross-contamination fix as ServingEngine tokens/s)
        self._latencies = collections.deque(maxlen=4096)
        # (finish-time, latency) pairs: the autoscaler's RECENT-p99
        # signal needs a time-windowed view, not the lifetime one
        self._lat_recent = collections.deque(maxlen=4096)
        # per-role windows (disaggregated fleets): the prefill pool's
        # latency is submit -> handoff, the decode pool's handoff ->
        # completion — each pool's autoscaler reads ITS OWN signal
        self._lat_prefill_recent = collections.deque(maxlen=4096)
        self._lat_decode_recent = collections.deque(maxlen=4096)
        # (finish-perf-t, {phase: seconds}, priority) per completion:
        # the per-request latency decomposition behind autoscale's
        # dominant_phase signal (all stamps one router clock, so the
        # phases telescope to the end-to-end latency exactly)
        self._phase_recent = collections.deque(maxlen=4096)
        self._g_configured.set(self.nreplicas)
        self._g_target.set(self.nreplicas)

        self._lock = threading.RLock()
        self._stop = threading.Event()
        # per-class ready queues; _pop_ready_locked interleaves them
        # weighted-fair (interactive_weight : 1)
        self._ready_hi = collections.deque()  # interactive
        self._ready_lo = collections.deque()  # batch (shed-first)
        self._wf_ticket = 0
        self._pending = {}                    # id -> FleetRequest (table)
        self._done = {}                       # id -> completed
        self._failed = {}                     # id -> failed (named reason)
        self.incidents = []                   # launch.incident_record + extras
        self.recoveries = []                  # {replica, incarnation, recovery_s}
        # bounded like _done/_failed: a fleet cycling on a short
        # cooldown for months must not grow (or deep-copy) forever
        self.scale_events = collections.deque(maxlen=256)
        self._next_rid = 0
        self._t0 = time.time()
        self._telemetry_next = 0.0
        self._q_sweep_next = 0.0

        self._replicas = []
        self._threads = []
        # resume path: an existing journal with a replica registry means
        # a previous router generation died here — its fleet SHAPE (role
        # plan, replica ids, ports, live worker pids) overrides the
        # constructor's, because the orphaned workers already embody it
        jstate = None
        if self.journal_dir:
            jstate = _journal.replay(self.journal_dir)
            if jstate.meta is not None:
                want = json.dumps(self.model_spec, sort_keys=True)
                got = jstate.meta.get("model_spec")
                if got is not None and got != want:
                    raise ValueError(
                        f"journal_dir {self.journal_dir!r} was written "
                        "for a DIFFERENT model_spec — resuming it would "
                        "replay requests onto a fleet with different "
                        "numerics; point a new fleet at a fresh dir")
        resume = bool(jstate is not None and jstate.replicas)
        if resume:
            plan = sorted(jstate.replicas.values(),
                          key=lambda v: v["rid"])
            self._role_plan = [v["role"] or "unified" for v in plan]
            self.nreplicas = len(plan)
            self.disaggregated = any(
                x != "unified" for x in self._role_plan)
            self._g_configured.set(self.nreplicas)
            self._g_target.set(self.nreplicas)
        try:
            if resume:
                for v in plan:
                    self._replicas.append(self._adopt_replica(v))
            else:
                for role in self._role_plan:
                    self._replicas.append(self._new_replica(role))
            if self.journal_dir:
                self._journal = _journal.JournalWriter(self.journal_dir)
                if resume:
                    self._recovering = True
                    self._recover_t0 = time.monotonic()
                    self._journal.append(
                        {"t": "resume", "wall": time.time(),
                         "replicas": sorted(jstate.replicas)})
                    self._apply_journal_state(jstate)
                else:
                    self._journal.append(
                        {"t": "meta", "wall": time.time(),
                         "model_spec": json.dumps(self.model_spec,
                                                  sort_keys=True),
                         "role_plan": list(self._role_plan)})
            for r in self._replicas:
                if r.adopted_pid is not None:
                    # live orphan: no spawn — wait for its reconnect
                    # (readopt hello), bounded like a slow boot
                    r.state = "starting"
                    r.spawn_deadline = time.monotonic() \
                        + self._readopt_timeout_s + 5.0
                    self._awaiting_readopt.add(r.id)
                    self._journal_replica(r)
                else:
                    self._spawn(r)
        except Exception:
            # a mid-fleet spawn failure (EMFILE, log_dir perms, ...) must
            # not leak the replicas already started — they would sit in
            # recv_msg forever, unsupervised (same guard as
            # launch.spawn_group)
            for r in self._replicas:
                if r.worker is not None:
                    r.worker["proc"].kill()
                    _launch.close_worker_log(r.worker)
                r.listener.close()
            if self._journal is not None:
                self._journal.close()
            raise
        for r in self._replicas:
            self._start_driver(r)

    @staticmethod
    def _normalize_roles(roles):
        """None, a per-replica role list, or a {"role": count} dict ->
        a validated role list (or None for the all-unified default)."""
        if roles is None:
            return None
        if isinstance(roles, dict):
            plan = []
            for role in ("prefill", "decode", "unified"):
                plan.extend([role] * int(roles.get(role, 0)))
            extra = set(roles) - set(ROLES)
            if extra:
                raise ValueError(f"unknown roles {sorted(extra)} — "
                                 f"expected among {ROLES}")
        else:
            plan = [str(r) for r in roles]
        bad = [r for r in plan if r not in ROLES]
        if bad:
            raise ValueError(f"unknown roles {bad} — expected among "
                             f"{ROLES}")
        if not plan:
            raise ValueError("roles names zero replicas")
        return plan

    def _new_replica(self, role="unified", rid=None, port=0):
        """Mint a replica on a fresh ephemeral port — or, on the resume
        path, re-bind the journal-RECORDED (rid, port) so the orphaned
        worker's reconnect loop finds its router where it left it."""
        lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            lst.bind(("127.0.0.1", int(port)))
            lst.listen(1)
        except OSError:
            lst.close()
            raise
        if rid is None:
            r = _Replica(self._next_rid, lst, role=role)
            self._next_rid += 1
        else:
            # resume: keep the journaled id; fresh mints stay above it
            # (replica ids are never reused, even across router deaths)
            r = _Replica(int(rid), lst, role=role)
            self._next_rid = max(self._next_rid, int(rid) + 1)
        return r

    def _adopt_replica(self, v):
        """One journal replica-registry entry -> a replica slot.  A
        still-live recorded pid is ADOPTED (recorded port re-bound, no
        spawn — the worker re-hellos through its reconnect loop); a
        dead pid, or a recorded port some other process took meanwhile,
        degrades to a normal fresh spawn on a fresh port."""
        role = v.get("role") or "unified"
        pid = int(v.get("pid") or 0)
        alive = _pid_alive(pid)
        if alive:
            try:
                r = self._new_replica(role, rid=v["rid"],
                                      port=v["port"])
            except OSError:
                r = self._new_replica(role, rid=v["rid"])
                alive = False
        else:
            r = self._new_replica(role, rid=v["rid"])
        r.incarnation = int(v.get("incarnation") or 0)
        if alive:
            r.adopted_pid = pid
        return r

    def _start_driver(self, r):
        r.thread = threading.Thread(target=self._drive, args=(r,),
                                    name=f"fleet-replica-{r.id}",
                                    daemon=True)
        r.thread.start()
        self._threads.append(r.thread)

    # ------------------------------------------------- journal plumbing
    def _jrec(self, rec):
        """Append one WAL record; a no-op without a journal (the
        ``journal_dir=None`` fleet pays nothing).  Callers may hold the
        fleet lock — the journal's own lock nests strictly inside it."""
        j = self._journal
        if j is not None:
            j.append(rec)

    def _journal_replica(self, r):
        self._jrec({"t": "replica", "rid": r.id, "port": r.port,
                    "pid": r.pid or 0, "role": r.role,
                    "incarnation": r.incarnation})

    @staticmethod
    def _admit_rec(req):
        return {"t": "admit", "id": req.id, "prompt": req.prompt,
                "max_new_tokens": req.max_new_tokens,
                "eos_token": req.eos_token,
                "deadline_s": req.deadline_s,
                "priority": req.priority,
                "phase": "prefill" if req.phase is not None else None,
                "admit_wall": req.admit_wall,
                "trace": req.trace_id}

    def _journal_snapshot(self):
        """Full live state as a record list — the compaction
        checkpoint.  Takes (and releases) the fleet lock itself; the
        caller must NOT hold it, so the lock order stays one-way
        (fleet -> journal, never back).  Finished ids come from the
        ALREADY-BOUNDED _done/_failed tables, so compaction drops acked
        ids past PADDLE_FLEET_DONE_RETENTION and the journal cannot
        grow without bound under sustained traffic."""
        recs = [{"t": "meta", "wall": time.time(),
                 "model_spec": json.dumps(self.model_spec,
                                          sort_keys=True),
                 "role_plan": list(self._role_plan)}]
        with self._lock:
            for r in self._replicas:
                if r.state == "removed":
                    continue
                # a dead process (mid-backoff, or a closing fleet)
                # checkpoints with pid 0: the slot survives — a
                # resuming router keeps the fleet SHAPE and spawns a
                # fresh child instead of adopting a corpse
                alive = self._proc_rc(r) is None
                recs.append(
                    {"t": "replica", "rid": r.id, "port": r.port,
                     "pid": (r.pid or 0) if alive else 0,
                     "role": r.role, "incarnation": r.incarnation})
            for req in self._pending.values():
                recs.append(self._admit_rec(req))
                if req.retries:
                    recs.append({"t": "requeue", "id": req.id,
                                 "retries": req.retries})
                if req.phase == "decode":
                    recs.append(
                        {"t": "flip", "id": req.id,
                         "first_token": req.first_token,
                         "kv_bytes": req.kv_bytes, "kv_hash": None,
                         "prefill_replica": req.prefill_replica})
            for req in self._done.values():
                recs.append(self._admit_rec(req))
                recs.append({"t": "done", "id": req.id,
                             "tokens": req.tokens,
                             "finish_reason": req.finish_reason})
            for req in self._failed.values():
                recs.append(self._admit_rec(req))
                recs.append({"t": "fail", "id": req.id,
                             "reason": req.error})
        return recs

    def _journal_maintain(self):
        """Driver-loop journal upkeep: compaction when the live segment
        outgrew its bound, then the batched fsync.  Both run with the
        fleet lock RELEASED (snapshot takes it internally)."""
        j = self._journal
        if j is None:
            return
        if j.compaction_due():
            j.compact(self._journal_snapshot())
        j.maybe_sync()

    def _apply_journal_state(self, st):
        """Replay a :class:`journal.JournalState` into the live tables
        (construction time, drivers not running yet).  Pending requests
        re-queue with their ORIGINAL deadlines; finished ids rebuild
        the dedupe/result tables; ids whose admit record was lost to
        corruption fail NAMED (``router_recovery``) — never silently."""
        now_wall, now_perf = time.time(), time.perf_counter()
        with self._lock:
            for rid in st.order:
                v = st.requests[rid]
                if v.get("rec") is None:
                    if v["status"] == "done" and v.get("tokens") \
                            is not None:
                        # admit lost but the completion survived: the
                        # RESULT is intact — rebuild it for client
                        # polls/dedupe under a stub prompt
                        req = FleetRequest([0], 1, request_id=rid)
                        req.tokens = [int(t) for t in v["tokens"]]
                        req.finish_reason = v.get("finish_reason")
                        req.done = True
                        req.finish_t = now_perf
                        self._done[rid] = req
                        self._evict_locked(self._done)
                        continue
                    # irrecoverable: no prompt to re-serve from
                    req = FleetRequest([0], 1, request_id=rid)
                    req.failed = True
                    req.error = ("router_recovery: admit record lost "
                                 "to journal corruption")
                    req.finish_t = now_perf
                    self._failed[rid] = req
                    self._evict_locked(self._failed)
                    self._inc("recovery_failures")
                    continue
                if v["status"] == "done":
                    req = rebuild_request(v, now_wall, now_perf)
                    req.tokens = [int(t) for t in v.get("tokens") or []]
                    req.finish_reason = v.get("finish_reason")
                    req.done = True
                    req.finish_t = now_perf
                    self._done[rid] = req
                    self._evict_locked(self._done)
                elif v["status"] == "failed":
                    req = rebuild_request(v, now_wall, now_perf)
                    req.failed = True
                    req.error = v.get("error") or "unknown"
                    req.finish_t = now_perf
                    self._failed[rid] = req
                    self._evict_locked(self._failed)
                else:
                    req = rebuild_request(v, now_wall, now_perf)
                    if not self.disaggregated \
                            and req.phase == "prefill":
                        req.phase = None   # unified fleets are phaseless
                    if self.prefix_sticky:
                        chain = [short_digest(k)
                                 for k in prompt_chain_keys(
                                     req.prompt, self._spec_page_size,
                                     self._hash_salt)]
                        chain = [d for d in chain if d]
                        req.prefix_chain = tuple(reversed(chain))
                        req.prefix_digest = chain[0] if chain else None
                    self._pending[req.id] = req
                    self._ready_queue_of(req).append(req)
                    self._inc("recovery_requeues")
            self._g_pending.set(len(self._pending))
            replayed = sorted(self._pending)
        # the restarted router files the crash postmortem its SIGKILLed
        # predecessor could not: every in-flight id it replayed, before
        # any of them redispatches (force: recovery is never coalesced
        # away by the rate limiter)
        tracing.dump("router_recovery", inflight=replayed,
                     extra={"recovery_requeues": len(replayed),
                            "journal_dir": self.journal_dir},
                     force=True)

    def _readopt_done(self, rid):
        """One awaited worker resolved (readopt hello landed, or its
        incident respawned it fresh).  When the LAST one resolves the
        recovery window closes: unclaimed decode-phase requests whose
        payload died with the old router flip back to the prefill
        phase (re-extract/re-prefill — recovery_rehandoffs), and
        ``router_recovery_s`` is stamped."""
        done = False
        with self._lock:
            self._awaiting_readopt.discard(rid)
            if self._recovering and not self._awaiting_readopt:
                self._recovering = False
                done = True
                claimed = set()
                for x in self._replicas:
                    claimed.update(x.inflight)
                for req in self._pending.values():
                    if req.phase == "decode" and req.kv is None \
                            and req.id not in claimed:
                        req.phase = "prefill" if self.disaggregated \
                            else None
                        req.first_token = None
                        req.migrate_from = req.migrate_to = None
                        self._inc("recovery_rehandoffs")
                self.router_recovery_s = round(
                    time.monotonic() - self._recover_t0, 3)
                self._g_router_recovery.set(self.router_recovery_s)
                self._inc("router_recoveries")
        if done:
            timeline.emit({"event": "fleet_router_recovery",
                           "recovery_s": self.router_recovery_s,
                           "readopts": len(self.readopt_events)})

    # ------------------------------------------------------------ intake
    def submit(self, prompt, max_new_tokens=16, eos_token=None,
               request_id=None, deadline_s=None, priority="interactive"):
        """Admit one request; returns its :class:`FleetRequest` handle.
        Re-submitting an id already pending/completed returns the
        EXISTING record (dedupe — a client retrying over a flaky hop
        can't double-serve).

        ``priority`` is the request's admission class:
        ``"interactive"`` (default) or ``"batch"`` (sheddable).  Past
        the global ``max_pending`` bound a batch arrival is rejected
        with :class:`FleetOverloaded`; an interactive arrival first
        DISPLACES a batch request (queued ones before in-flight ones —
        the victim fails with the named reason ``shed_overload``) and is
        rejected only when no batch work exists in the fleet."""
        if deadline_s is None:
            deadline_s = self.request_deadline_s
        req = FleetRequest(prompt, max_new_tokens, eos_token=eos_token,
                           request_id=request_id, deadline_s=deadline_s,
                           priority=priority)
        with self._lock:
            for table in (self._pending, self._done, self._failed):
                if req.id in table:
                    return table[req.id]
            if len(self._pending) >= self.max_pending:
                if req.priority == "interactive" \
                        and self._shed_batch_victim_locked(req.id):
                    pass          # a batch request made room, named shed
                else:
                    self._inc("sheds")
                    self._inc(f"sheds_{req.priority}")
                    tracing.dump(
                        "shed", inflight=[req.id],
                        extra={"pending": len(self._pending),
                               "max_pending": self.max_pending,
                               "priority": req.priority})
                    raise FleetOverloaded(
                        f"pending table at max_pending "
                        f"{self.max_pending} "
                        f"({len(self._done)} completed so far) — shed "
                        "and retry with backoff")
            if self.disaggregated:
                req.phase = "prefill"     # every request prefills first
            if self.prefix_sticky:
                chain = [short_digest(k) for k in prompt_chain_keys(
                    req.prompt, self._spec_page_size, self._hash_salt)]
                chain = [d for d in chain if d]   # drop the part tail
                req.prefix_chain = tuple(reversed(chain))
                req.prefix_digest = chain[0] if chain else None
            self._pending[req.id] = req
            (self._ready_hi if req.priority == "interactive"
             else self._ready_lo).append(req)
            self._inc("requests_admitted")
            self._g_pending.set(len(self._pending))
            req.trace_id = tracing.mint()
            self._jrec(self._admit_rec(req))
            req.admit_t = tracing.event(
                "admit", trace_id=req.trace_id, request_id=req.id,
                priority=req.priority, phase=req.phase,
                prompt_len=len(req.prompt))["t"]
        return req

    def _shed_batch_victim_locked(self, for_id):
        """Displace one batch request to admit an interactive arrival
        under overload: newest QUEUED batch first (zero sunk cost), then
        newest IN-FLIGHT batch (cancelled on its replica).  Returns True
        when a victim was shed.  The victim fails with the named reason
        ``shed_overload`` — graceful degradation is loud, never
        silent."""
        victim, owner = None, None
        while self._ready_lo:
            cand = self._ready_lo.pop()            # newest queued batch
            if cand.done or cand.failed or cand.id not in self._pending:
                continue       # stale entry (mass-fail, dedupe): drop,
            victim = cand      # it frees no pending slot
            break
        if victim is None:
            for r in self._replicas:
                for q in r.inflight.values():
                    if q.priority == "batch" and not (q.done or q.failed) \
                            and q.id in self._pending \
                            and (victim is None
                                 or q.submit_t > victim.submit_t):
                        victim, owner = q, r
            if owner is not None:
                owner.inflight.pop(victim.id, None)
                owner.pending_cancel.append(victim.id)
        if victim is None:
            return False
        self._inc("sheds")
        self._inc("sheds_batch")
        self._fail_locked(
            victim, f"shed_overload: batch request displaced by "
                    f"interactive admission {for_id!r} at max_pending "
                    f"{self.max_pending}")
        return True

    def _pop_ready_locked(self):
        """The next dispatchable request, weighted-fair across the
        priority classes: ``interactive_weight`` interactive pops per
        batch pop while both queues are non-empty; a lone class drains
        at full rate."""
        hi, lo = self._ready_hi, self._ready_lo
        if hi and lo:
            if self._wf_ticket >= self.interactive_weight:
                self._wf_ticket = 0
                return lo.popleft()
            self._wf_ticket += 1
            return hi.popleft()
        if hi:
            return hi.popleft()
        if lo:
            return lo.popleft()
        return None

    def _ready_queue_of(self, req):
        return self._ready_hi if req.priority == "interactive" \
            else self._ready_lo

    # ------------------------------------------------- replica lifecycle
    def _worker_env(self, r):
        env = dict(self.env_base)
        env["PADDLE_FLEET_PORT"] = str(r.port)
        env["PADDLE_FLEET_REPLICA"] = str(r.id)
        env["PADDLE_FLEET_ROLE"] = r.role
        # faults rank/restart filters + telemetry rank = the replica id
        env["PADDLE_TRAINER_ID"] = str(r.id)
        env["PADDLE_RESTART_COUNT"] = str(r.incarnation)
        env["PADDLE_FLEET_MODEL"] = json.dumps(self.model_spec,
                                               sort_keys=True)
        if self.jit_cache_dir:
            env["PADDLE_JIT_CACHE_DIR"] = os.path.abspath(
                self.jit_cache_dir)
        if self.aot_cache_dir:
            env["PADDLE_AOT_CACHE_DIR"] = os.path.abspath(
                self.aot_cache_dir)
        if self.telemetry_dir:
            env["PADDLE_TELEMETRY_DIR"] = os.path.abspath(
                self.telemetry_dir)
        # a worker is ONE engine process, never a jax.distributed member
        env.pop("PADDLE_MASTER", None)
        if self.journal_dir:
            # journaled fleets survive router death: workers hold a
            # bounded reconnect window instead of exiting on EOF
            env["PADDLE_FLEET_READOPT_TIMEOUT_S"] = str(
                self._readopt_timeout_s)
        return env

    def _spawn(self, r):
        log_path = None
        if self.log_dir:
            log_path = os.path.join(self.log_dir,
                                    f"replica{r.id}.log")
        r.adopted_pid = None          # a fresh child replaces any orphan
        r.worker = _launch.spawn_worker(
            self.worker_argv, self._worker_env(r), log_path=log_path)
        r.state = "starting"
        r.spawn_deadline = time.monotonic() + self.spawn_timeout_s
        self._journal_replica(r)

    def _proc_rc(self, r):
        """The replica process's exit code if it is DEAD, else None.
        Spawned children report through their Popen handle; adopted
        orphans (no handle) probe with signal 0 — their synthetic
        ``rc=-1`` only marks death, the real code died with the old
        router."""
        if r.worker is not None:
            return r.worker["proc"].poll()
        if r.adopted_pid:
            return None if _pid_alive(r.adopted_pid) else -1
        return -1

    def _await_hello(self, r):
        """Accept the (re)spawned worker's connection + hello — or, on
        the resume path, the adopted orphan's RE-hello (``readopt``).
        Bounded by spawn_timeout_s; a worker dying while starting is an
        incident like any other."""
        r.listener.settimeout(0.25)
        while not self._stop.is_set():
            # queued requests must not outlive their deadlines just
            # because every replica is still booting (never-dispatched
            # deadline sweep — no dispatch loop runs while we sit here)
            self._sweep_queued_deadlines()
            if r.draining:
                return             # being removed while starting: bail
            if self._proc_rc(r) is not None:
                raise _ReplicaGone(
                    f"worker exited rc={self._proc_rc(r)} "
                    "before hello")
            if time.monotonic() > r.spawn_deadline:
                raise _ReplicaGone(
                    f"no hello within spawn_timeout_s="
                    f"{self.spawn_timeout_s}")
            try:
                conn, _ = r.listener.accept()
            except socket.timeout:
                continue
            conn.settimeout(self.heartbeat_s)
            try:
                hello = recv_msg(conn)
            except (OSError, ValueError) as e:
                conn.close()
                raise _ReplicaGone(f"bad hello: {e}") from e
            # numeric-contract attestation (ISSUE 9): a replica serving
            # a different quant mode / KV dtype than the spec asked for
            # would return budget-different tokens for re-queued
            # requests — refuse it like any other unhealthy replica
            stats = hello.get("stats") or {}
            mismatch = self._contract_mismatch(stats, r.role)
            if mismatch is not None:
                conn.close()
                # deterministic config error, not a crash: relaunching
                # the identical spec can only mismatch again, so spend
                # the whole restart budget now — the replica goes (and
                # stays) down with the incident named, instead of
                # burning minutes of kill/backoff/relaunch churn
                r.restarts_used = self.max_restarts
                raise _ReplicaGone(
                    f"numeric contract mismatch: replica hello reports "
                    f"(quant, kv_dtype, spec_mode, tp, pp, role)="
                    f"{mismatch[0]} but the fleet assigned "
                    f"{mismatch[1]} — config error, replica will not "
                    "be relaunched")
            r.conn = conn
            r.hello = hello
            r.last_stats = stats
            r.state = "healthy"
            self._g_up.inc(1)
            compile_att = hello.get("compile") or {}
            if hello.get("readopt"):
                self._handle_readopt(r, hello, compile_att)
            elif r.id in self._awaiting_readopt:
                # an awaited orphan resolved through a normal hello
                # (respawned fresh): the recovery window must not wait
                # on it any longer
                self._readopt_done(r.id)
            if r.scale_ev is not None:
                # close the open scale-up record: the bench's
                # warm-scale-up attestation reads these
                r.scale_ev["hello_t"] = time.time()
                r.scale_ev["boot_s"] = hello.get("boot_s")
                r.scale_ev["warm_cache_misses"] = (hello.get(
                    "persistent_cache") or {}).get("misses")
                # AOT cold-start attestation (ISSUE 14): the joiner's
                # actual backend-compile count + artifact traffic — an
                # artifact-warm replica reports xla_compiles == 0
                r.scale_ev["xla_compiles"] = compile_att.get(
                    "xla_compiles")
                r.scale_ev["aot"] = compile_att.get("aot")
                r.scale_ev = None
            if r.incident_t is not None:
                rec = round(time.monotonic() - r.incident_t, 3)
                r.incident_t = None
                self._g_recovery.set(rec)
                with self._lock:
                    self.recoveries.append({
                        "replica": r.id, "incarnation": r.incarnation,
                        "recovery_s": rec,
                        "warm_cache_misses": (hello.get(
                            "persistent_cache") or {}).get("misses"),
                        "xla_compiles": compile_att.get("xla_compiles"),
                        "aot": compile_att.get("aot"),
                    })
            return

    def _handle_readopt(self, r, hello, compile_att):
        """Reconcile a surviving worker's RE-hello: every in-flight id
        it claims moves from the replayed ready queue back onto this
        replica's in-flight table (the work keeps running — never
        re-dispatched, never double-served); ids nobody claims stay
        queued and re-dispatch normally.  The finished backlog needs no
        special casing: it re-sends on the next step reply and the
        at-least-once dedupe absorbs duplicates."""
        r.adopted_pid = int(hello.get("pid") or 0) or r.adopted_pid
        claims, stale = [], []
        with self._lock:
            for cid in hello.get("inflight") or []:
                cid = str(cid)
                req = self._pending.get(cid)
                if req is None or req.done or req.failed:
                    stale.append(cid)     # finished pre-crash: cancel
                    continue
                if any(cid in x.inflight for x in self._replicas):
                    continue              # first claimant keeps it
                try:
                    self._ready_queue_of(req).remove(req)
                except ValueError:
                    continue              # not queued: dispatched already
                req.replica = r.id
                if r.id not in req.replicas_tried:
                    req.replicas_tried.append(r.id)
                r.inflight[cid] = req
                claims.append(cid)
                tracing.event("readopt_claim", trace_id=req.trace_id,
                              request_id=cid, replica=r.id,
                              incarnation=r.incarnation)
            r.pending_cancel.extend(stale)
        self._inc("readopts")
        ev = {"replica": r.id, "incarnation": r.incarnation,
              "claims": len(claims), "stale_claims": len(stale),
              "xla_compiles": compile_att.get("xla_compiles"),
              "warm_cache_misses": (hello.get("persistent_cache")
                                    or {}).get("misses")}
        with self._lock:
            self.readopt_events.append(ev)
        self._journal_replica(r)
        timeline.emit({"event": "fleet_readopt", **ev})
        self._readopt_done(r.id)

    def _incident(self, r, reason):
        """Exactly-once per incarnation (driver thread is the sole
        owner): record, kill whatever is left, re-queue the in-flight
        table, schedule the backoff relaunch."""
        proc = r.worker["proc"] if r.worker else None
        if proc is not None and proc.poll() is None:
            proc.kill()                  # a suspect replica must not keep
            try:                         # serving half-connected
                proc.wait(timeout=10)
            except Exception:                              # noqa: BLE001
                pass
        elif proc is None and r.adopted_pid \
                and _pid_alive(r.adopted_pid):
            # an adopted orphan gone suspect (readopt window expired,
            # heartbeat miss, refused re-hello): same rule — kill it,
            # the relaunch spawns a proper child in its place
            try:
                os.kill(r.adopted_pid, signal.SIGKILL)
            except OSError:
                pass
        rc = proc.poll() if proc is not None else None
        r.adopted_pid = None
        if r.conn is not None:
            try:
                r.conn.close()
            except OSError:
                pass
            r.conn = None
        if r.state == "healthy":
            self._g_up.inc(-1)
        was = r.state
        r.state = "dead"
        if r.incident_t is None:         # first detection this incarnation
            r.incident_t = time.monotonic()
        rec = _launch.incident_record(
            r.id, rc, r.incarnation,
            log_path=(r.worker or {}).get("log_path"), t0=self._t0)
        rec["replica"] = r.id
        rec["reason"] = reason
        rec["state_at_failure"] = was
        self._inc("incidents")
        with self._lock:
            self.incidents.append(rec)
            victims = list(r.inflight.values())
            r.inflight.clear()
            for req in victims:
                self._requeue_locked(req, f"replica {r.id} {reason}")
        timeline.emit({"event": "fleet_incident", "replica": r.id,
                       "incarnation": r.incarnation, "reason": reason,
                       "exit_code": rc, "requeued": len(victims)})
        # flight recorder: the postmortem names the in-flight ids and
        # their last hop, not just a requeue counter bump
        tracing.dump("replica_incident",
                     inflight=[q.id for q in victims],
                     extra={"replica": r.id, "reason": str(reason),
                            "exit_code": rc,
                            "incarnation": r.incarnation})
        # a recovery window must not wait forever on a replica that
        # just died instead of re-helloing
        self._readopt_done(r.id)
        r.next_spawn_t = time.monotonic() + _launch.backoff_delay(
            self.restart_backoff_s, r.restarts_used)

    def _maybe_relaunch(self, r):
        if r.restarts_used >= self.max_restarts:
            # budget spent: this replica stays down.  If EVERY replica is
            # permanently down the pending table can never drain — fail
            # the stranded requests with a named reason (never silent).
            with self._lock:
                if all(x.state == "dead"
                       and x.restarts_used >= self.max_restarts
                       for x in self._replicas):
                    for req in list(self._pending.values()):
                        self._fail_locked(
                            req, "fleet_down: every replica exhausted "
                                 "its restart budget")
            self._stop.wait(0.5)
            return
        wait = r.next_spawn_t - time.monotonic()
        if wait > 0:
            # the backoff window MUST be shutdown-interruptible (ISSUE
            # 11 satellite): wait on the stop event — never time.sleep —
            # and in short slices so a concurrent remove_replica()
            # (draining flip) is noticed promptly too
            self._stop.wait(min(wait, 0.25))
            return
        r.restarts_used += 1
        r.incarnation += 1
        self._inc("replica_restarts")
        self._spawn(r)

    # ------------------------------------------------------------ driving
    def _rpc(self, r, msg):
        """One request/response exchange with heartbeat accounting.  Any
        failure — dead process, EOF, oversize/undecodable frame, or a
        reply missing past the heartbeat deadline — raises
        _ReplicaGone."""
        rc = self._proc_rc(r)
        if rc is not None:
            raise _ReplicaGone(f"process exited rc={rc}")
        # clock-skew pairs: every traced RPC carries the sender's
        # tracing-clock stamp ("ts"); the receiver's rpc_recv echo of it
        # is what trace assembly bounds per-process offsets with
        traced = tracing.enabled()
        if traced:
            msg["ts"] = tracing.now()
        try:
            send_msg(r.conn, msg)
            resp = recv_msg(r.conn)
            if traced and resp.get("ts") is not None:
                tracing.event("rpc_recv", peer_sent=resp["ts"],
                              peer_pid=resp.get("pid"), replica=r.id,
                              op=msg.get("op"))
            return resp
        except socket.timeout as e:
            self._inc("heartbeat_misses")
            raise _ReplicaGone(
                f"heartbeat miss: no reply to '{msg.get('op')}' within "
                f"{self.heartbeat_s}s") from e
        except (OSError, ValueError, ConnectionError) as e:
            self._inc("rpc_errors")
            raise _ReplicaGone(f"rpc failed: {type(e).__name__}: {e}") \
                from e

    def _contract_mismatch(self, stats, role="unified"):
        """None when the replica's reported numeric/behavior contract
        (quant mode, kv_dtype, spec_mode, tp degree, pp stages, role —
        echoed in every engine ``stats()`` / worker reply) matches the fleet
        spec's; else ``(got, want)`` for the incident record.  Requests
        re-queued across replicas assume identical numerics — a
        mixed-contract fleet would silently break the token-exact retry
        guarantee; and though speculation is token-exact by design, a
        spec/non-spec mix would skew every per-replica latency/compile
        attestation the bench joins on, so spec_mode is part of the
        attested contract too (ISSUE 13).  The tuple grew tp + role in
        ISSUE 15: a replica sharded over a different tp degree computes
        different reduction orders (greedy ties can flip between
        retries), and a replica serving the wrong ROLE would either
        decode work it was never handed KV for or silently prefill on
        the decode pool — both refuse at hello like mixed int8/fp32.
        It grew pp in ISSUE 20 for the same reduction-order reason: the
        stage step's psum('tp')-per-block partial sums depend on the
        (pp, tp) decomposition, so a mixed-pp fleet is a mixed-numerics
        fleet and refuses at hello exactly like mixed-tp."""
        want = (self.model_spec.get("quant"),
                self.model_spec.get("kv_dtype"),
                self.model_spec.get("spec_mode"),
                int(self.model_spec.get("tp") or 1),
                int(self.model_spec.get("pp") or 1),
                role or "unified")
        got = (stats.get("quant"), stats.get("kv_dtype"),
               stats.get("spec_mode"), int(stats.get("tp") or 1),
               int(stats.get("pp") or 1),
               stats.get("role") or "unified")
        return None if got == want else (got, want)

    def _capacity(self, r):
        """How many more requests this replica can hold, judged from
        the serving.* numbers its last reply carried — the least-loaded
        routing signal.

        Paged replicas are keyed on their FREE-PAGE fraction: free
        pages divided by the replica's observed pages-per-request
        footprint bounds how many more requests it can physically KV —
        a replica whose slots look free but whose page pool is pinned
        (fragmented-but-counted-free slots) no longer wins routing.
        Non-paged replicas fall back to the slot-occupancy headroom."""
        st = r.last_stats or {}
        slots = int(st.get("slots", self._slots))
        cap = slots + self.dispatch_queue_depth - len(r.inflight)
        free_pages = st.get("pages_free")
        if free_pages is not None:
            ppr = max(1, int(st.get("pages_per_request_est") or 1))
            # pages_free already excludes pages held by ADMITTED work;
            # only the in-flight requests not yet holding pages (still
            # in the worker queue / in transit) claim from the free set
            unpaged = max(0, len(r.inflight)
                          - int(st.get("slot_occupancy") or 0))
            cap = min(cap, int(free_pages) // ppr - unpaged)
        return max(0, cap)

    def _phase_ok(self, req, r):
        """Role-aware capacity routing (ISSUE 15): a prefill replica
        only takes prefill-phase requests, a decode replica only
        handed-off (payload-carrying) ones; unified replicas take the
        phase-less stream of a unified fleet — plus, with migration on
        (ISSUE 17), the phased legs of a hot-prefix copy, each pinned
        to its replica (prefill at the chain's hot owner, decode at the
        cold target) unless that replica is gone/unhealthy/draining, in
        which case any unified replica serves it (a dead pin must never
        strand a request)."""
        if r.role == "unified":
            if req.phase is None:
                return True
            pin = (req.migrate_from if req.phase == "prefill"
                   else req.migrate_to)
            if pin is None:
                return False
            if pin == r.id:
                return True
            t = self._replica_by_id(pin)
            return t is None or t.state != "healthy" or t.draining
        return req.phase == ("prefill" if r.role == "prefill"
                             else "decode")

    def _sticky_defers_locked(self, req, r, now):
        """Prefix-sticky verdict for dispatching ``req`` on ``r``
        (caller holds the lock): True -> hold the request for the
        chain-owning replica (it has the pages — device or host tier);
        False -> serve it HERE, counting ``prefix_routed`` when r IS
        the owner and ``prefix_fallbacks`` when the owner exists but is
        dead/draining/out of capacity (least-loaded wins — stickiness
        must never add latency, only save prefill).

        The chain digests are tried DEEPEST first: an exact repeat
        matches its whole chain's sole holder (memo + pages -> fault
        back, no re-prefill); a fresh prompt sharing only the pooled
        prefix falls through to the shared head page's owner."""
        target = None
        for d in req.prefix_chain:
            target = self._prefix_index.get(d)
            if target is not None:
                break
        if target is None:
            return False              # unknown chain: normal routing
        if target == r.id:
            self._inc("prefix_routed")
            self._note_route_locked(req, r, now)
            return False
        t = self._replica_by_id(target)
        if (t is None or t.state != "healthy" or t.draining
                or t.role != r.role or self._capacity(t) <= 0):
            self._inc("prefix_fallbacks")
            return False
        return True

    def _note_route_locked(self, req, r, now):
        """Hotspot bookkeeping: count sticky routes per digest inside
        ``migrate_window_s``; past ``migrate_hot_routes`` of them, turn
        THIS dispatch into a migration — its prefill leg pins to the
        hot owner (prefix hits make it nearly free), the extracted
        chain parks on the router, and the decode leg pins to the
        coldest healthy replica, which the index now owns."""
        if not self.migrate_enabled:
            return
        ent = self._route_counts.get(req.prefix_digest)
        if ent is None or now - ent[1] > self.migrate_window_s:
            ent = [0, now]
        ent[0] += 1
        self._route_counts[req.prefix_digest] = ent
        self._route_counts.move_to_end(req.prefix_digest)
        while len(self._route_counts) > 4096:
            self._route_counts.popitem(last=False)
        if ent[0] < self.migrate_hot_routes:
            return
        cold = None
        for x in self._replicas:
            if (x.id == r.id or x.state != "healthy" or x.draining
                    or x.role != "unified"):
                continue
            if cold is None or self._capacity(x) > self._capacity(cold):
                cold = x
        if cold is None or self._capacity(cold) <= 0:
            return                    # nowhere colder: stay sticky
        req.phase = "prefill"
        req.migrate_from = r.id
        req.migrate_to = cold.id
        self._prefix_index[req.prefix_digest] = cold.id
        self._prefix_index.move_to_end(req.prefix_digest)
        self._route_counts[req.prefix_digest] = [0, now]

    def _update_prefix_index(self, r, stats):
        """Fold a replica's step-stats digest sketch into the fleet
        prefix index (digest -> replica id, bounded LRU) — the
        router-side half of prefix-aware routing.

        FIRST writer wins: once a healthy replica owns a digest, a
        second replica advertising the same chain (two same-prefix
        requests raced before the index warmed) does NOT steal it —
        otherwise the index flaps between advertisers on every stats
        reply and stickiness averages out to random.  Ownership moves
        only when the owner stops being usable, or when hot-prefix
        migration repoints the entry deliberately."""
        digs = (stats or {}).get("chain_digests")
        if not self.prefix_sticky or not digs:
            return
        with self._lock:
            idx = self._prefix_index
            for d in digs:
                cur = idx.get(d)
                if cur is not None and cur != r.id:
                    owner = self._replica_by_id(cur)
                    if owner is not None and owner.state == "healthy" \
                            and not owner.draining:
                        idx.move_to_end(d)
                        continue        # sticky: owner keeps the chain
                idx[d] = r.id
                idx.move_to_end(d)
            while len(idx) > 8192:
                idx.popitem(last=False)

    def _pick_dispatch(self, r):
        if r.draining:
            return []          # drain-then-stop: no new work, ever
        now = time.perf_counter()
        batch = []
        with self._lock:
            cap = self._capacity(r)
            skipped = []
            while len(batch) < cap:
                req = self._pop_ready_locked()
                if req is None:
                    break
                if req.done or req.failed or req.id not in self._pending:
                    continue                    # cancelled/deduped away
                if req.expired(now):
                    self._fail_locked(req, "deadline_exceeded")
                    self._inc("deadline_exceeded")
                    continue
                if not self._phase_ok(req, r):
                    skipped.append(req)         # the other pool's work
                    continue
                if req.not_before > now:
                    skipped.append(req)         # still backing off
                    continue
                if (self.prefix_sticky and req.prefix_chain
                        and (req.phase is None
                             or (req.phase == "prefill"
                                 and req.migrate_from is None))
                        and self._sticky_defers_locked(req, r, now)):
                    skipped.append(req)         # the chain's owner's work
                    continue
                if req.phase == "decode" and req.kv is None:
                    # journal replay: this request's handoff payload
                    # died with the old router.  While the re-adoption
                    # window is open its claimant may still appear —
                    # hold; after the window _readopt_done flipped the
                    # stragglers, so this late safety net flips too
                    # and re-examines the (now prefill-phase) request
                    if self._recovering:
                        skipped.append(req)
                        continue
                    req.phase = "prefill" if self.disaggregated \
                        else None
                    req.first_token = None
                    req.migrate_from = req.migrate_to = None
                    self._inc("recovery_rehandoffs")
                    if not self._phase_ok(req, r):
                        skipped.append(req)
                        continue
                if req.retries:
                    self._inc("retries")
                req.replica = r.id
                req.replicas_tried.append(r.id)
                r.inflight[req.id] = req
                batch.append(req)
                self._jrec({"t": "dispatch", "id": req.id,
                            "rep": r.id})
                rec = tracing.event(
                    "dispatch", trace_id=req.trace_id,
                    request_id=req.id, replica=r.id, phase=req.phase,
                    retry=req.retries, priority=req.priority)
                if req.dispatch_t is None:
                    req.dispatch_t = rec["t"]
            # restore skipped work at the HEAD in reverse pop order —
            # queue order is preserved exactly, so a handed-off request
            # _handoff put at the front (mid-flight work) keeps its
            # place instead of rotating behind fresh arrivals every
            # time the OTHER pool's driver examines it
            for req in reversed(skipped):
                self._ready_queue_of(req).appendleft(req)
        return batch

    def _rpc_submit(self, r, batch):
        items = []
        for q in batch:
            item = {"id": q.id, "prompt": q.prompt,
                    "max_new_tokens": q.max_new_tokens,
                    "eos_token": q.eos_token}
            if q.phase is not None:
                item["phase"] = q.phase
                if q.phase == "decode":
                    item["first_token"] = q.first_token
                    item["kv"] = q.kv
                    q.kv_ships += 1
                    if q.kv_ships > 1:
                        # the same payload crossing again: a decode
                        # replica died/dropped it — zero-lost re-ships
                        self._inc("handoff_reships")
                    rec = tracing.event(
                        "ship", trace_id=q.trace_id, request_id=q.id,
                        replica=r.id, kv_bytes=q.kv_bytes,
                        reship=q.kv_ships > 1, priority=q.priority)
                    if q.ship_t is None:
                        q.ship_t = rec["t"]
            if q.trace_id is not None:
                item["trace"] = q.trace_id
            items.append(item)
        resp = self._rpc(r, {"op": "submit", "requests": items})
        rejected = resp.get("rejected") or []
        with self._lock:
            for rej in rejected:
                req = r.inflight.pop(rej["id"], None)
                if req is None:
                    continue
                if rej.get("permanent"):
                    # a request the engine can NEVER serve (prompt too
                    # long for the ladder, ...) — failing fast beats
                    # bouncing it between replicas forever
                    self._inc("rejects_permanent")
                    self._fail_locked(
                        req, f"rejected: {rej.get('err', 'unserveable')}")
                else:                           # back-pressure: try later
                    if (req.phase == "decode" and req.kv_ships
                            and "handoff_drop" not in
                            (rej.get("err") or "")):
                        # a ServingQueueFull bounce is not a lost
                        # handoff — the payload never landed, nothing
                        # died; un-count the ship so routine
                        # back-pressure can't read as re-ships (the
                        # injected handoff_drop fault, which names
                        # itself in the reject, still counts)
                        req.kv_ships -= 1
                    req.not_before = time.perf_counter() + 0.05
                    self._ready_queue_of(req).append(req)
                    tracing.event("requeue", trace_id=req.trace_id,
                                  request_id=req.id, replica=r.id,
                                  reason="backpressure")

    def _handle_step_resp(self, r, resp):
        for fin in resp.get("finished") or []:
            # dup or not, ack it — the worker's buffer must drain
            self._complete(fin, r)
            r.pending_ack.append(fin["id"])
        with self._lock:
            for rid in resp.get("requeue") or []:
                req = r.inflight.pop(rid, None)
                if req is not None:
                    self._requeue_locked(
                        req, f"replica {r.id} aborted mid-step: "
                             f"{resp.get('error')}")
        r.last_stats = resp.get("stats") or r.last_stats
        self._update_prefix_index(r, r.last_stats)

    def _handoff(self, fin, r):
        """A prefill replica finished a request's PREFILL phase: park
        the KV payload + first token on the pending-table entry, flip
        it to the decode phase, and put it back at the ready-queue head
        (it is mid-flight work — it must not queue behind fresh
        arrivals).  The payload stays on the entry until the FINAL
        completion, so a decode-side death re-ships the same pages."""
        rid = fin["id"]
        with self._lock:
            req = self._pending.get(rid)
            r.inflight.pop(rid, None)
            if req is None or req.done or req.failed \
                    or req.phase == "decode":
                # already handed off / completed: a re-sent handoff
                # record (lost ack) must not double-queue the request
                self._inc("dup_completions")
                return False
            req.phase = "decode"
            req.first_token = int(fin["first_token"])
            req.kv = fin.get("kv")
            req.kv_bytes = int(fin.get("kv_bytes") or 0)
            req.kv_ships = 0
            req.prefill_replica = r.id
            req.replica = None
            req.decode_t0 = time.perf_counter()
            self._lat_prefill_recent.append(
                (req.decode_t0, req.decode_t0 - req.submit_t,
                 req.priority))
            self._inc("kv_handoffs")
            self._inc("kv_handoff_bytes", req.kv_bytes)
            # journal the payload's content hash + owner, NOT its
            # bytes: recovery re-extracts or re-prefills (PR-17
            # fault-back), it never replays KV from disk
            self._jrec({"t": "flip", "id": req.id,
                        "first_token": req.first_token,
                        "kv_bytes": req.kv_bytes,
                        "kv_hash": (_journal.payload_hash(req.kv)
                                    if req.kv is not None else None),
                        "prefill_replica": r.id})
            if req.migrate_to is not None:
                # a hot-prefix migration's extract leg just landed: the
                # parked pages are the chain COPY headed for the cold
                # replica (content-hashed on inject like any handoff)
                self._inc("prefix_migrations")
                self._inc("migration_bytes", req.kv_bytes)
            self._ready_queue_of(req).appendleft(req)
            req.park_t = tracing.event(
                "park", trace_id=req.trace_id, request_id=req.id,
                replica=r.id, kv_bytes=req.kv_bytes,
                priority=req.priority)["t"]
        return True

    def _complete(self, fin, r):
        if fin.get("phase") == "prefill":
            return self._handoff(fin, r)
        rid = fin["id"]
        with self._lock:
            req = self._pending.pop(rid, None)
            r.inflight.pop(rid, None)
            if req is None:
                self._inc("dup_completions")
                return False
            req.tokens = [int(t) for t in fin.get("tokens") or []]
            req.finish_reason = fin.get("finish_reason")
            req.replica = r.id
            req.done = True
            req.kv = None             # retention tables must not pin KV
            req.finish_t = time.perf_counter()
            self._done[rid] = req
            self._evict_locked(self._done)
            self._inc("requests_completed")
            # tokens ride the ack record: a post-restart client poll
            # still finds results completed before the crash
            self._jrec({"t": "done", "id": rid, "tokens": req.tokens,
                        "finish_reason": req.finish_reason})
            lat = req.finish_t - req.submit_t
            self._h_latency.observe(lat)
            self._latencies.append(lat)
            self._lat_recent.append((req.finish_t, lat, req.priority))
            if req.decode_t0 is not None:
                self._lat_decode_recent.append(
                    (req.finish_t, req.finish_t - req.decode_t0,
                     req.priority))
            ack_t = tracing.event(
                "ack", trace_id=req.trace_id, request_id=req.id,
                replica=r.id, tokens=len(req.tokens),
                finish_reason=req.finish_reason, priority=req.priority,
                latency_s=round(lat, 6))["t"]
            phases = self._phase_split(req, ack_t)
            if phases:
                self._phase_recent.append(
                    (req.finish_t, phases, req.priority))
            self._g_pending.set(len(self._pending))
        return True

    @staticmethod
    def _phase_split(req, ack_t):
        """Router-side per-request latency decomposition from the
        tracing-clock boundary stamps.  Every boundary is used exactly
        once, so the phases TELESCOPE: their sum equals ack - admit —
        the attribution is exact, not sampled.  The decode phase here
        is the router's view (ship -> ack: inject + decode + reply
        hop); the assembled trace splits it finer with replica-side
        events."""
        if req.admit_t is None or req.dispatch_t is None:
            return None
        ph = {"queue": req.dispatch_t - req.admit_t}
        if req.park_t is not None:
            ph["prefill"] = req.park_t - req.dispatch_t
            if req.ship_t is not None:
                ph["parked"] = req.ship_t - req.park_t
                ph["decode"] = ack_t - req.ship_t
            else:
                ph["decode"] = ack_t - req.park_t
        else:
            ph["service"] = ack_t - req.dispatch_t
        return {k: round(max(v, 0.0), 6) for k, v in ph.items()}

    def _evict_locked(self, table):
        """Keep a finished-request table inside done_retention (dicts
        iterate in insertion order: the oldest entries go first).  The
        dedupe window shrinks with it — callers re-using request ids
        older than the retention horizon are re-served, not deduped."""
        while len(table) > self.done_retention:
            table.pop(next(iter(table)))

    def _requeue_locked(self, req, reason, charge_retry=True):
        """Back into the ready queue (bounded retries + backoff) — the
        no-request-dropped invariant's working end.

        ``charge_retry=False`` is the VOLUNTARY path (scale-down drain
        handoff): the request did nothing wrong and the fleet chose to
        move it, so it must not consume the failure-retry budget — a
        request bounced by several scale-downs can never be failed
        ``retries_exhausted`` — and it redispatches without backoff."""
        if req.done or req.failed:
            return
        if charge_retry:
            req.retries += 1
            self._inc("requeues")
            if req.retries > self.max_retries:
                self._fail_locked(req, f"retries_exhausted after "
                                       f"{self.max_retries}: {reason}")
                return
            req.not_before = time.perf_counter() + self.retry_backoff_s \
                * (2 ** (req.retries - 1))
        req.replica = None
        self._jrec({"t": "requeue", "id": req.id,
                    "retries": req.retries})
        tracing.event("requeue", trace_id=req.trace_id,
                      request_id=req.id, retries=req.retries,
                      reason=str(reason)[:160])
        # re-queued work jumps the line: it has already waited longest
        self._ready_queue_of(req).appendleft(req)

    def _fail_locked(self, req, reason):
        self._pending.pop(req.id, None)
        if req.done or req.failed:
            return
        req.failed = True
        req.kv = None                 # retention tables must not pin KV
        req.error = reason
        req.finish_t = time.perf_counter()
        self._failed[req.id] = req
        self._evict_locked(self._failed)
        self._inc("requests_failed")
        self._g_pending.set(len(self._pending))
        self._jrec({"t": "fail", "id": req.id, "reason": reason})
        tracing.event("fail", trace_id=req.trace_id,
                      request_id=req.id, reason=str(reason)[:160],
                      priority=req.priority)
        if str(reason).startswith("shed_overload"):
            # the shed postmortem names its victim (rate-limited: a
            # shed storm is one dump, the counters carry the volume)
            tracing.dump("shed", inflight=[req.id],
                         extra={"reason": str(reason)[:200]})

    def _sweep_deadlines(self, r):
        now = time.perf_counter()
        with self._lock:
            for rid, req in list(r.inflight.items()):
                if req.expired(now):
                    r.inflight.pop(rid)
                    r.pending_cancel.append(rid)
                    self._inc("deadline_exceeded")
                    self._fail_locked(req, "deadline_exceeded")

    def _sweep_queued_deadlines(self):
        """Deadline enforcement for NEVER-DISPATCHED requests (ISSUE 11
        satellite): a request stranded in the router queue — every
        replica busy, dead, or still booting — must fail fast at its
        deadline, not wait for a dispatch attempt that may never come.
        Every driver thread calls this (including from inside the
        _await_hello poll loop, where no dispatch runs at all); the
        time gate keeps the sweep O(queue) per 50ms, not per loop."""
        now = time.perf_counter()
        # gate read OUTSIDE the lock: every driver thread calls this per
        # loop iteration, and the common case is a no-op that must not
        # contend the router lock (a stale read at worst re-checks once)
        if now < self._q_sweep_next:
            return
        with self._lock:
            if now < self._q_sweep_next:
                return
            self._q_sweep_next = now + 0.05
            for dq in (self._ready_hi, self._ready_lo):
                expired = [q for q in dq if q.expired(now)]
                for req in expired:
                    dq.remove(req)
                    self._inc("deadline_exceeded")
                    self._fail_locked(req, "deadline_exceeded")

    def _publish_telemetry(self):
        """Router snapshot (rank = ROUTER_RANK, far past any replica id
        an elastic fleet can mint) into the shared telemetry dir, so
        merge_from_dir shows the fleet.* counters next to the
        per-replica serving stats.  Written directly — NOT via
        timeline.configure(), whose process-global state would race
        across the driver threads."""
        if not self.telemetry_dir:
            return
        with self._lock:
            now = time.monotonic()
            if now < self._telemetry_next:
                return
            self._telemetry_next = now + 2.0
        try:
            from ..observability import aggregate
            snap = aggregate.snapshot_record(rank=ROUTER_RANK)
            os.makedirs(self.telemetry_dir, exist_ok=True)
            path = os.path.join(self.telemetry_dir,
                                f"snapshot_rank{ROUTER_RANK}.json")
            tmp = f"{path}.tmp{threading.get_ident()}"
            with open(tmp, "w") as f:
                json.dump(snap, f, sort_keys=True)
            os.replace(tmp, path)
        except Exception:                                  # noqa: BLE001
            pass              # telemetry must never hurt serving

    def _drive(self, r):
        """Per-replica driver thread: relaunch when dead, handshake when
        starting, otherwise dispatch + step + health-check.  All
        incidents for this replica funnel through here (exactly-once).
        A draining replica (scale-down) stops dispatching, keeps
        stepping until its in-flight table empties (bounded by
        drain_timeout_s), then retires — zero-lost holds through every
        removal."""
        while not self._stop.is_set():
            try:
                self._sweep_queued_deadlines()
                if r.draining:
                    if r.state != "healthy":
                        break      # dead/starting: nothing to finish
                    if not r.inflight:
                        break                          # drained clean
                    if r.drain_t0 is not None and \
                            time.monotonic() - r.drain_t0 \
                            > self.drain_timeout_s:
                        break      # _retire re-queues the leftovers
                if r.state == "dead":
                    self._maybe_relaunch(r)
                    continue
                if r.state == "starting":
                    self._await_hello(r)
                    continue
                self._sweep_deadlines(r)
                batch = self._pick_dispatch(r)
                if batch:
                    self._rpc_submit(r, batch)
                st = r.last_stats or {}
                busy = bool(batch or r.inflight
                            or st.get("queue_depth")
                            or st.get("slot_occupancy"))
                msg = {"op": "step" if busy else "ping",
                       "max_steps": self.steps_per_rpc if busy else 0,
                       "ack": r.pending_ack[:],
                       "cancel": r.pending_cancel[:]}
                r.pending_ack.clear()
                r.pending_cancel.clear()
                resp = self._rpc(r, msg)
                self._handle_step_resp(r, resp)
                self._publish_telemetry()
                self._journal_maintain()
                if not busy:
                    self._stop.wait(self.heartbeat_idle_s)
            except _ReplicaGone as e:
                self._incident(r, str(e))
            except Exception as e:                         # noqa: BLE001
                # a router-side bug must not strand the replica's
                # in-flight requests: treat as an incident and relaunch
                self._incident(r, f"driver error: "
                                  f"{type(e).__name__}: {e}")
        if r.draining and not self._stop.is_set():
            self._retire(r)

    # ------------------------------------------------ elastic lifecycle
    def _replica_by_id(self, rid):
        with self._lock:
            return next((x for x in self._replicas if x.id == int(rid)),
                        None)

    def add_replica(self, role=None):
        """Scale UP: mint, spawn, and drive one more supervised replica;
        returns its id (replica ids are minted monotonically and never
        reused).  With a shared ``PADDLE_JIT_CACHE_DIR`` the newcomer
        warm-boots from the persistent compilation cache — its hello's
        cache-miss count lands on the scale event record, which the
        bench asserts is 0.  ``role`` ("prefill"/"decode") picks the
        pool a disaggregated fleet grows; the coherence rule holds
        elastically too (no unified joiners on a disaggregated fleet
        and vice versa)."""
        if role is None:
            role = "prefill" if self.disaggregated else "unified"
        if self.disaggregated and role not in ("prefill", "decode"):
            raise ValueError(
                f"a disaggregated fleet only grows prefill/decode "
                f"replicas, not {role!r}")
        if not self.disaggregated and role != "unified":
            raise ValueError(
                f"a unified fleet only grows unified replicas, not "
                f"{role!r} — build it with roles= to disaggregate")
        with self._lock:
            # registration (not the slow spawn) happens under the lock:
            # close() snapshots _replicas under it, so once we are past
            # this block a racing close() WILL see the replica
            if self._stop.is_set():
                raise RuntimeError("fleet is closed")
            r = self._new_replica(role)
            # stamped from the tracing clock (one wall anchor +
            # monotonic deltas): an NTP step mid-run can never reorder
            # scale records against the trace events they sit between
            ev = {"action": "scale_up", "replica": r.id, "role": role,
                  "t": tracing.now()}
            self.scale_events.append(ev)
            r.scale_ev = ev
            self._replicas.append(r)
            self.nreplicas = len(self._replicas)
            self._g_configured.set(self.nreplicas)
        try:
            self._spawn(r)
        except Exception:
            with self._lock:
                self._replicas.remove(r)
                self.nreplicas = len(self._replicas)
                self._g_configured.set(self.nreplicas)
                ev["error"] = "spawn failed"
            r.listener.close()
            raise
        if self._stop.is_set():
            # close() raced the spawn: its teardown sweep may have seen
            # r.worker as None, so the orphan is OURS to kill (both
            # killing is harmless — every step is idempotent)
            r.worker["proc"].kill()
            _launch.close_worker_log(r.worker)
            try:
                r.listener.close()
            except OSError:
                pass
            ev["error"] = "fleet closed during spawn"
            raise RuntimeError("fleet is closed")
        self._inc("scale_ups")
        self._start_driver(r)
        timeline.emit({"event": "fleet_scale_up", "replica": r.id,
                       "replicas_configured": self.nreplicas})
        return r.id

    def remove_replica(self, rid, wait=False, timeout=None):
        """Scale DOWN, drain-then-stop: the replica immediately stops
        receiving dispatches, finishes its in-flight work (re-queued
        onto survivors past ``drain_timeout_s``), then its worker exits
        and the replica unregisters — an admitted request can never be
        lost to a scale-down.  Asynchronous by default (the replica's
        own driver thread performs the drain); ``wait=True`` blocks
        until the replica is gone.  Refuses to remove the last
        non-draining replica (use :meth:`close` to tear down)."""
        with self._lock:
            r = self._replica_by_id(rid)
            if r is None:
                raise KeyError(f"no replica {rid} in this fleet")
            if not r.draining:
                live = [x for x in self._replicas if not x.draining]
                if len(live) <= 1:
                    raise ValueError(
                        "refusing to remove the last serving replica — "
                        "close() tears the whole fleet down")
                if self.disaggregated and sum(
                        1 for x in live if x.role == r.role) <= 1:
                    raise ValueError(
                        f"refusing to remove the last {r.role} replica "
                        "— the other phase's requests would strand "
                        "forever")
                r.draining = True
                r.drain_t0 = time.monotonic()
                self._inc("scale_downs")
                self.scale_events.append(
                    {"action": "scale_down", "replica": r.id,
                     "role": r.role, "t": tracing.now()})
                timeline.emit({"event": "fleet_scale_down",
                               "replica": r.id,
                               "inflight_at_drain": len(r.inflight)})
            thread = r.thread
        if wait and thread is not None:
            thread.join(timeout if timeout is not None
                        else self.drain_timeout_s + self.heartbeat_s
                        + 10)
            if thread.is_alive():
                raise TimeoutError(
                    f"replica {rid} did not drain within the wait")

    def _stop_replica_proc(self, r, grace=2.0):
        """Stop whatever process backs this replica: spawned children
        through the launch hooks, adopted orphans (no Popen handle) via
        SIGTERM-then-SIGKILL."""
        if r.worker is not None:
            try:
                _launch.stop_worker(r.worker, term_grace=grace)
            except Exception:                              # noqa: BLE001
                pass
            _launch.close_worker_log(r.worker)
            return
        pid = r.adopted_pid
        if not pid or not _pid_alive(pid):
            return
        try:
            os.kill(pid, signal.SIGTERM)
        except OSError:
            return
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline and _pid_alive(pid):
            time.sleep(0.05)
        if _pid_alive(pid):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass

    def _retire(self, r):
        """Finalize a scale-down (driver thread only): re-queue whatever
        the drain could not finish, politely stop the worker (the final
        ack set rides the shutdown message), release the socket/log, and
        unregister the replica."""
        with self._lock:
            victims = list(r.inflight.values())
            r.inflight.clear()
            for req in victims:
                self._inc("drain_requeues")
                self._requeue_locked(req, f"replica {r.id} removed",
                                     charge_retry=False)
        if r.conn is not None:
            try:
                r.conn.settimeout(2.0)
                send_msg(r.conn, {"op": "shutdown",
                                  "ack": r.pending_ack[:]})
            except OSError:
                pass
            try:
                r.conn.close()
            except OSError:
                pass
            r.conn = None
        self._stop_replica_proc(r)
        try:
            r.listener.close()
        except OSError:
            pass
        if r.state == "healthy":
            self._g_up.inc(-1)
        r.state = "removed"
        self._jrec({"t": "replica", "rid": r.id, "port": r.port,
                    "state": "removed"})
        with self._lock:
            if r in self._replicas:
                self._replicas.remove(r)
            if r.thread in self._threads:
                # a long-lived elastic fleet must not accumulate one
                # dead Thread per scale cycle
                self._threads.remove(r.thread)
            self.nreplicas = len(self._replicas)
            self._g_configured.set(self.nreplicas)
            ev = next((e for e in reversed(self.scale_events)
                       if e.get("replica") == r.id
                       and e["action"] == "scale_down"
                       and "done_t" not in e), None)
            if ev is not None:
                ev["done_t"] = time.time()
                ev["drain_requeues"] = len(victims)
        if self.telemetry_dir:
            # ids are never reused, so a retired replica's snapshot
            # would read as a live-but-frozen rank in merged telemetry
            # forever — drop it
            try:
                os.unlink(os.path.join(self.telemetry_dir,
                                       f"snapshot_rank{r.id}.json"))
            except OSError:
                pass
        timeline.emit({"event": "fleet_replica_removed",
                       "replica": r.id,
                       "drain_requeues": len(victims)})

    def scaledown_victim(self, role=None):
        """The cheapest replica to remove right now, or None: a dead or
        still-booting replica first (it serves nothing), else the
        healthy replica with the least in-flight work.  Already-draining
        replicas are never re-picked; the last live replica is never
        offered — nor, on a disaggregated fleet, the last replica of
        any role.  ``role`` restricts the pick to one pool (the
        per-role autoscaler loops)."""
        with self._lock:
            live = [r for r in self._replicas if not r.draining]
            if len(live) <= 1:
                return None
            counts = {}
            for r in live:
                counts[r.role] = counts.get(r.role, 0) + 1
            cands = [r for r in live
                     if (role is None or r.role == role)
                     and (not self.disaggregated
                          or counts[r.role] > 1)]
            if not cands:
                return None
            unhealthy = [r for r in cands if r.state != "healthy"]
            if unhealthy:
                return unhealthy[0].id
            return min(cands, key=lambda r: len(r.inflight)).id

    def autoscale_signals(self, window_s=15.0, role=None):
        """One consistent snapshot of the control signals the
        :mod:`~paddle_tpu.inference.autoscale` loop keys on: router
        backlog, pending-table fraction (the shed horizon), per-replica
        occupancy, and the p99 of completions inside the trailing
        ``window_s`` (lifetime percentiles can never scale DOWN — a
        window can).

        ``role`` scopes the snapshot to ONE pool of a disaggregated
        fleet (ISSUE 15): replicas/occupancy of that role only, backlog
        counted over the queued requests in that pool's PHASE, and the
        latency window swapped for the pool's own — submit->handoff for
        the prefill pool, handoff->completion for the decode pool — so
        each role's scaling loop reads signals the other pool's load
        cannot pollute."""
        now = time.perf_counter()
        want_phase = {"prefill": "prefill", "decode": "decode"}.get(role)
        with self._lock:
            if self._recovering:
                # a router mid-recovery (workers re-helloing, replayed
                # backlog not yet reconciled) reads as a traffic spike
                # it is not: hand the autoscaler a QUIESCENT snapshot —
                # hold, don't thrash — instead of raising or scaling on
                # ghosts (ISSUE 18 satellite; extends the PR-11 tick
                # isolation law)
                reps = [r for r in self._replicas if not r.draining
                        and (role is None or r.role == role)]
                return {
                    "role": role, "recovering": True,
                    "backlog": 0, "pending": 0,
                    "pending_fraction": 0.0,
                    "configured": len(reps),
                    "healthy": sum(1 for r in reps
                                   if r.state == "healthy"),
                    "occupancy": 0.0, "p99_s": None, "p50_s": None,
                    "window_n": 0,
                    "sheds": self._counts.get("sheds", 0),
                    "accepted_tokens_per_step": 0.0,
                    "spill_pressure": 0.0,
                    "dominant_phase": None,
                }
            if want_phase is None:
                backlog = len(self._ready_hi) + len(self._ready_lo)
            else:
                backlog = sum(1 for dq in (self._ready_hi,
                                           self._ready_lo)
                              for q in dq if q.phase == want_phase)
            pending = len(self._pending)
            reps = [r for r in self._replicas if not r.draining
                    and (role is None or r.role == role)]
            healthy = sum(1 for r in reps if r.state == "healthy")
            occ = []
            accepted = []
            spill = []
            for r in reps:
                if r.state != "healthy":
                    continue
                st = r.last_stats or {}
                slots = max(int(st.get("slots") or self._slots), 1)
                occ.append(min(
                    (int(st.get("slot_occupancy") or 0)
                     + int(st.get("queue_depth") or 0)) / slots, 2.0))
                # host-tier fill (ISSUE 17): a fleet whose tiers run
                # full is thrashing spills — re-prefills are imminent,
                # so the autoscaler treats it as an up-pressure signal
                f = st.get("host_tier_fill")
                if f is not None:
                    spill.append(float(f))
                # speculative replicas echo their live
                # serving.accepted_tokens_per_step in every reply — the
                # autoscaler normalizes backlog by it so spec fleets
                # scale on accepted-tokens/s, not steps/s (ISSUE 14)
                a = st.get("accepted_tokens_per_step")
                if a:
                    accepted.append(float(a))
            window = {"prefill": self._lat_prefill_recent,
                      "decode": self._lat_decode_recent}.get(
                          role, self._lat_recent)
            lats = sorted(lat for (t, lat, _p) in window
                          if now - t <= window_s)
            sheds = self._counts.get("sheds", 0)
            configured = len(reps)
            # per-phase attribution (ISSUE 19): which lifecycle phase
            # dominates recent completions — the scale decision record
            # cites it, so "scaled up on p99" also says WHY p99 rose
            phase_sums = {}
            for (t, ph, _p) in self._phase_recent:
                if now - t <= window_s:
                    for k, v in ph.items():
                        phase_sums[k] = phase_sums.get(k, 0.0) + v
        dominant = max(phase_sums, key=phase_sums.get) \
            if phase_sums else None
        return {
            "role": role, "recovering": False,
            "backlog": backlog, "pending": pending,
            "pending_fraction": pending / max(self.max_pending, 1),
            "configured": configured, "healthy": healthy,
            "occupancy": (sum(occ) / len(occ)) if occ else 0.0,
            "p99_s": metrics.nearest_rank_percentile(lats, 99),
            "p50_s": metrics.nearest_rank_percentile(lats, 50),
            "window_n": len(lats), "sheds": sheds,
            "accepted_tokens_per_step": (
                round(sum(accepted) / len(accepted), 4)
                if accepted else 0.0),
            "spill_pressure": max(spill) if spill else 0.0,
            "dominant_phase": dominant,
        }

    # ------------------------------------------------------------- public
    def kill_replica(self, rid, sig=signal.SIGKILL):
        """Hard-kill a replica's process (chaos harness / bench).  The
        driver thread detects the death and runs the normal incident
        path — requeue, backoff, relaunch."""
        r = self._replica_by_id(rid)
        if r is None:
            raise KeyError(f"no replica {rid} in this fleet")
        pid = r.pid
        if pid is not None:
            try:
                os.kill(pid, sig)
            except ProcessLookupError:
                pass
        return pid

    def replicas_up(self):
        # under the lock: an elastic fleet mutates _replicas at runtime,
        # and an unlocked list iteration racing a remove() can skip an
        # element and undercount
        with self._lock:
            return sum(1 for r in self._replicas
                       if r.state == "healthy")

    def await_healthy(self, n=None, timeout=60.0, poll=0.05):
        """Block until at least ``n`` replicas (default all) are
        healthy; returns the healthy count (which may be short if
        ``timeout`` expires — callers assert)."""
        want = self.nreplicas if n is None else int(n)
        deadline = time.monotonic() + timeout
        while self.replicas_up() < want \
                and time.monotonic() < deadline:
            time.sleep(poll)
        return self.replicas_up()

    def pending_count(self):
        with self._lock:
            return len(self._pending)

    def results(self):
        """Snapshot of every finished request: ``(done, failed,
        pending_count)`` where ``done`` maps id -> tokens +
        finish_reason and ``failed`` maps id -> the NAMED error.  The
        supervisor's poll RPC (and tests) read this — a wire-safe copy,
        never live Request objects."""
        with self._lock:
            done = {rid: {"tokens": [int(t) for t in r.tokens],
                          "finish_reason": r.finish_reason}
                    for rid, r in self._done.items()}
            failed = {rid: str(r.error) for rid, r in
                      self._failed.items()}
            return done, failed, len(self._pending)

    def replica_pids(self):
        """id -> live worker pid (spawned child or adopted orphan;
        None while starting/dead).  The chaos bench asserts these are
        UNCHANGED across a router kill — warm re-adoption, not replica
        restarts."""
        with self._lock:
            return {r.id: r.pid for r in self._replicas
                    if not r.draining}

    def replica_compile_counts(self):
        """id -> the worker's CUMULATIVE backend-compile count, from
        its latest stats report.  Because re-adoption keeps the same
        worker processes (same cumulative counters), before-kill ==
        after-drain is exactly the 'zero XLA compiles during
        re-adoption' attestation."""
        with self._lock:
            return {r.id: (r.last_stats or {}).get("xla_compiles")
                    for r in self._replicas if not r.draining}

    def drain(self, timeout=None, poll=0.02):
        """Block until every admitted request completed or failed.
        Returns (done, failed) dicts by id.  Raises TimeoutError with
        the stranded ids when ``timeout`` expires — silence is the one
        thing a durability layer may never produce."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            with self._lock:
                if not self._pending:
                    return dict(self._done), dict(self._failed)
                stranded = list(self._pending)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"{len(stranded)} requests still pending after "
                    f"{timeout}s: {stranded[:8]}{'...' if len(stranded) > 8 else ''}")
            time.sleep(poll)

    def _inc(self, key, v=1):
        """Count into the process-global fleet.* registry family AND
        this fleet's own dict — :meth:`stats` reads the latter."""
        self._stats.inc(key, v)
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + v

    def stats(self):
        """THIS fleet's counters + live state, one dict (the
        process-global family — all fleets pooled — is
        :func:`fleet_stats`)."""
        out = dict(self._counts)
        with self._lock:
            out.update(
                pending=len(self._pending), completed=len(self._done),
                failed=len(self._failed),
                ready=len(self._ready_hi) + len(self._ready_lo),
                ready_interactive=len(self._ready_hi),
                ready_batch=len(self._ready_lo),
                replicas_up=self.replicas_up(),
                replicas=self.nreplicas,
                disaggregated=self.disaggregated,
                prefix_sticky=self.prefix_sticky,
                prefix_index_size=len(self._prefix_index),
                replicas_by_role={
                    role: sum(1 for r in self._replicas
                              if r.role == role and not r.draining)
                    for role in sorted({r.role
                                        for r in self._replicas})},
                incidents_detail=list(self.incidents),
                recoveries=list(self.recoveries),
                scale_events=[dict(e) for e in self.scale_events],
                journaled=self._journal is not None,
                journal_size_bytes=(self._journal.size_bytes()
                                    if self._journal is not None
                                    else 0),
                recovering=self._recovering,
                router_recovery_s=self.router_recovery_s,
                readopt_events=[dict(e)
                                for e in self.readopt_events])
        # THIS fleet's window, not the shared registry histogram — a
        # coexisting fleet's traffic must not shape these percentiles
        with self._lock:
            data = sorted(self._latencies)
        out["latency_s"] = {
            "p50": metrics.nearest_rank_percentile(data, 50),
            "p99": metrics.nearest_rank_percentile(data, 99),
            "count": len(data)}
        return out

    def recovery_time_s(self):
        """Seconds from the LAST incident's detection to its replacement
        replica serving again (None until a recovery happened) — the
        bench's ``fleet_recovery_time_s`` metric."""
        with self._lock:
            if not self.recoveries:
                return None
            return self.recoveries[-1]["recovery_s"]

    def _crash(self):
        """TEST/BENCH ONLY: die the way a SIGKILL'd router does —
        drop every connection and listener mid-conversation, abandon
        the journal WITHOUT its close-time fsync, kill nothing, fail
        nothing, tell the workers nothing.  The workers see EOF and
        (on a journaled fleet) enter their re-adoption window; a new
        ``ServingFleet(journal_dir=...)`` in the same or another
        process then exercises the real recovery path in-process."""
        self._stop.set()
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=self.heartbeat_s + 5)
        with self._lock:
            reps = list(self._replicas)
        for r in reps:
            if r.conn is not None:
                try:
                    r.conn.close()
                except OSError:
                    pass
                r.conn = None
            try:
                r.listener.close()
            except OSError:
                pass
        if self._journal is not None:
            self._journal.abandon()
            self._journal = None

    def close(self):
        """Tear the fleet down: stop driver threads, best-effort
        graceful worker shutdown, then kill.  Pending requests are
        failed with reason ``fleet_shutdown`` (never silently lost).
        Interruptible everywhere — a replica parked in its
        restart-backoff window wakes on the stop event immediately,
        never sleeping out the backoff (:meth:`shutdown` is the same
        call by its production name)."""
        self._stop.set()
        with self._lock:
            # snapshot: a concurrent _retire() prunes this list
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=self.heartbeat_s + 5)
        with self._lock:
            reps = list(self._replicas)
        for r in reps:
            if r.conn is not None:
                try:
                    r.conn.settimeout(1.0)
                    send_msg(r.conn, {"op": "shutdown"})
                except OSError:
                    pass
                try:
                    r.conn.close()
                except OSError:
                    pass
            self._stop_replica_proc(r)
            try:
                r.listener.close()
            except OSError:
                pass
            if r.state == "healthy":
                self._g_up.inc(-1)
                r.state = "dead"
        with self._lock:
            for req in list(self._pending.values()):
                self._fail_locked(req, "fleet_shutdown")
        if self._journal is not None:
            # a CLEAN shutdown leaves no live state behind: compact to
            # the (now pending-free) checkpoint so a later fleet on the
            # same dir resumes results/dedupe, not ghost replicas
            self._journal.compact(self._journal_snapshot())
            self._journal.close()
            self._journal = None
        if self.telemetry_dir:
            # release the router's claim on the utility-rank event file
            # (a later engine in THIS process writes at its own rank)
            timeline.set_rank_override(None)

    # the production name for the same teardown; tests assert it
    # returns promptly even mid-restart-backoff
    shutdown = close

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
        return False
