"""SLO-driven autoscaling for the serving fleet (ISSUE 11 tentpole).

The :class:`Autoscaler` is a small control loop over
:meth:`ServingFleet.autoscale_signals`: it drives the fleet's replica
count from the telemetry the PR-4 layer already aggregates — router
queue backlog, pending-table fraction (the shed horizon), replica
slot/queue occupancy, and the trailing-window request p99 against the
``PADDLE_FLEET_SLO_P99_S`` target — in the Clipper/production tradition
where the SLO itself is the control signal, not raw CPU.

Design points the tests pin down:

* **scale up BEFORE shedding** — the pending-fraction trigger fires at
  ``pending_headroom`` (default 70%) of ``max_pending``, well inside
  the :class:`~paddle_tpu.inference.fleet.FleetOverloaded` horizon, so
  capacity arrives before the router starts refusing work.
* **hysteresis + cooldown** — scaling up needs ``up_ticks`` consecutive
  breach ticks (default 1: bursts are urgent), scaling down
  ``down_ticks`` consecutive idle ticks (default 8: de-provisioning is
  patient), and any action arms a ``cooldown_s`` window during which
  the loop only observes.  The combination keeps a noisy signal from
  flapping the fleet.
* **bounds** — replica count stays inside
  ``[min_replicas, max_replicas]`` no matter what the signals (or the
  ``autoscale_flap`` chaos fault) demand.
* **graceful scale-down** — victims come from
  :meth:`ServingFleet.scaledown_victim` (dead replicas first, then the
  least-loaded healthy one) and are removed via the fleet's
  drain-then-stop path, so de-provisioning can never lose a request.
* **wedge-proof** — every tick is exception-isolated (counted in
  ``autoscale.tick_errors``); a failed ``add_replica`` or a replica
  SIGKILLed mid-scale-up leaves the loop running and the next tick
  re-evaluates from fresh signals.

Telemetry rides the ``autoscale.*`` registry family plus the
``fleet.replicas_target`` gauge; every decision is a JSONL
``autoscale_decision`` timeline event and a record on
:attr:`Autoscaler.decisions`.

Env knobs (all overridable per-instance): ``PADDLE_FLEET_SLO_P99_S``,
``PADDLE_FLEET_MIN_REPLICAS``, ``PADDLE_FLEET_MAX_REPLICAS``,
``PADDLE_FLEET_SCALE_COOLDOWN_S``.
"""
from __future__ import annotations

import collections
import threading
import time

from ..observability import metrics, timeline, tracing
from ..testing import faults as _faults
from .fleet import _env_float, _env_int

__all__ = ["Autoscaler", "autoscale_stats", "role_autoscalers"]


def role_autoscalers(fleet, prefill=None, decode=None, **common):
    """The disaggregated composition (ISSUE 15 satellite): one
    independent :class:`Autoscaler` per role pool, each reading its own
    signals (prefill: submit->handoff latency + prefill-phase backlog;
    decode: handoff->completion latency + decode-phase backlog) and
    scaling only its own replicas.  ``prefill``/``decode`` are
    per-pool kwarg overrides layered over ``common``.  Returns the
    ``[prefill_scaler, decode_scaler]`` pair — start/stop them together
    (each is a context manager)."""
    out = []
    for role, over in (("prefill", prefill), ("decode", decode)):
        kw = dict(common)
        kw.update(over or {})
        out.append(Autoscaler(fleet, role=role, **kw))
    return out


def _stats_family():
    return metrics.stats_family("autoscale", {
        "ticks": 0, "scale_ups": 0, "scale_downs": 0,
        "holds_cooldown": 0, "holds_bounds": 0, "tick_errors": 0,
        "ticks_quiescent": 0,
        "flap_forced": 0, "up_signals_p99": 0, "up_signals_backlog": 0,
        "up_signals_pending": 0, "up_signals_occupancy": 0,
        "up_signals_spill": 0})


def autoscale_stats():
    """The process-global ``autoscale.*`` counter family."""
    return dict(_stats_family())


class Autoscaler:
    """Drive ``fleet``'s replica count from its own SLO telemetry.

    Use as a context manager (``with Autoscaler(fleet) as a:``) or call
    :meth:`start` / :meth:`stop`; :meth:`tick` is the whole control law
    and is directly callable for deterministic tests — ``fleet`` only
    needs ``autoscale_signals() / add_replica() / remove_replica() /
    scaledown_victim()``.
    """

    def __init__(self, fleet, *, slo_p99_s=None, min_replicas=None,
                 max_replicas=None, cooldown_s=None, interval_s=0.25,
                 window_s=15.0, up_backlog_per_replica=2.0,
                 pending_headroom=0.7, hi_occupancy=0.85,
                 lo_occupancy=0.35, up_ticks=1, down_ticks=8,
                 slo_down_margin=0.5, spill_up=None, role=None):
        self.fleet = fleet
        # per-role-pool scaling loop (ISSUE 15): role="prefill"/"decode"
        # scopes every signal AND every action to that pool of a
        # disaggregated fleet — the canonical composition is one
        # Autoscaler per role (see :func:`role_autoscalers`), each with
        # its own thresholds (prefill pools key on submit->handoff
        # latency + prefill backlog, decode pools on handoff->complete
        # latency + decode backlog).  None = the whole (unified) fleet.
        if role is not None and role not in ("prefill", "decode"):
            raise ValueError(
                f"role must be 'prefill', 'decode', or None, got "
                f"{role!r}")
        self.role = role
        self.slo_p99_s = slo_p99_s if slo_p99_s is not None \
            else _env_float("PADDLE_FLEET_SLO_P99_S", 5.0)
        self.min_replicas = max(1, min_replicas if min_replicas is not None
                                else _env_int("PADDLE_FLEET_MIN_REPLICAS",
                                              1))
        self.max_replicas = max_replicas if max_replicas is not None \
            else _env_int("PADDLE_FLEET_MAX_REPLICAS", 4)
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas {self.max_replicas} < min_replicas "
                f"{self.min_replicas}")
        self.cooldown_s = cooldown_s if cooldown_s is not None \
            else _env_float("PADDLE_FLEET_SCALE_COOLDOWN_S", 5.0)
        self.interval_s = float(interval_s)
        self.window_s = float(window_s)
        self.up_backlog_per_replica = float(up_backlog_per_replica)
        self.pending_headroom = float(pending_headroom)
        self.hi_occupancy = float(hi_occupancy)
        self.lo_occupancy = float(lo_occupancy)
        self.up_ticks = int(up_ticks)
        self.down_ticks = int(down_ticks)
        self.slo_down_margin = float(slo_down_margin)
        # host-tier spill pressure (ISSUE 17): any replica's pinned-host
        # KV tier past this fill fraction means evicted chains are
        # about to fall off the host LRU too — re-prefills imminent —
        # so more replicas (more device pages, more tier bytes) help
        self.spill_up = spill_up if spill_up is not None \
            else _env_float("PADDLE_FLEET_SPILL_UP", 0.9)

        self._stats = _stats_family()
        # the autoscale.* family is process-global; mirror every
        # count into THIS instance's dict (stats() reports it) so a
        # coexisting autoscaler's ticks are never misattributed —
        # same discipline as ServingFleet._inc
        self._counts = {k: 0 for k in self._stats}
        self._g_target = metrics.gauge("fleet.replicas_target")
        self._stop = threading.Event()
        self._thread = None
        self._cool_until = 0.0
        self._up_streak = 0
        self._down_streak = 0
        # bounded: a loop on a short cooldown must not grow forever
        self.decisions = collections.deque(maxlen=256)

    # ------------------------------------------------------------ control
    def tick(self, now=None):
        """One control decision.  Returns ``"up"``, ``"down"``, or
        ``None`` (hold).  Exception-isolated: a failing fleet call is
        counted and swallowed so the loop can never wedge."""
        now = time.monotonic() if now is None else now
        self._inc("ticks")
        try:
            return self._tick_inner(now)
        except Exception as e:                             # noqa: BLE001
            self._inc("tick_errors")
            timeline.emit({"event": "autoscale_tick_error",
                           "error": f"{type(e).__name__}: {e}"})
            return None

    def _tick_inner(self, now):
        # role=None stays a positional-only call (test fakes and older
        # fleet stand-ins don't know the kwarg)
        try:
            sig = (self.fleet.autoscale_signals(self.window_s)
                   if self.role is None
                   else self.fleet.autoscale_signals(self.window_s,
                                                     role=self.role))
        except Exception as e:                             # noqa: BLE001
            # a router generation swap mid-tick (ISSUE 18): the fleet
            # object is being torn down / replayed under us.  That is
            # scheduled maintenance, not a control-law failure — hold
            # quiescently (ticks_quiescent, NOT tick_errors) and let
            # the next tick read the new generation's signals
            self._inc("ticks_quiescent")
            self._up_streak = self._down_streak = 0
            timeline.emit({"event": "autoscale_quiescent",
                           "error": f"{type(e).__name__}: {e}"})
            return None
        if sig.get("recovering"):
            # the new router generation is still re-adopting workers:
            # its backlog/occupancy snapshot is deliberately zeroed
            # (fleet.autoscale_signals), so acting on it would
            # scale-down a busy fleet.  Hold, reset streaks — stale
            # pre-crash streaks must not carry a decision across a
            # recovery window
            self._inc("ticks_quiescent")
            self._up_streak = self._down_streak = 0
            return None
        target = sig["configured"]
        self._g_target.set(target)

        # bounds are restorative, not just gates: a fleet outside
        # [min, max] (operator remove_replica, construction below the
        # floor) is steered back regardless of load signals — streaks
        # don't apply, cooldown still does (no restore-thrash)
        if target < self.min_replicas or target > self.max_replicas:
            if now < self._cool_until:
                self._inc("holds_cooldown")
                return None
            direction = "up" if target < self.min_replicas else "down"
            return self._act(direction, sig, now, reasons=("bounds",))

        forced = _faults.autoscale_flap() if _faults.active() else None
        if forced is not None:
            # chaos: force the DECISION every tick — bounds still apply,
            # cooldown deliberately does not (that is the race the fault
            # exists to amplify)
            self._inc("flap_forced")
            return self._act(forced, sig, now, reasons=("flap",))

        reasons_up = []
        p99 = sig["p99_s"]
        if self.slo_p99_s and p99 is not None and p99 > self.slo_p99_s:
            reasons_up.append("p99")
            self._inc("up_signals_p99")
        healthy = max(sig["healthy"], 1)
        # ISSUE 14 satellite: speculative fleets drain backlog in
        # accepted-TOKENS/s, not steps/s — a replica committing ~4
        # tokens per row-verify clears a queue ~4x sooner, so the
        # backlog threshold scales with the fleet's live
        # serving.accepted_tokens_per_step (1.0 when absent or
        # non-speculative: behavior unchanged)
        spec_rate = max(
            float(sig.get("accepted_tokens_per_step") or 0.0), 1.0)
        if sig["backlog"] > (self.up_backlog_per_replica * healthy
                             * spec_rate):
            reasons_up.append("backlog")
            self._inc("up_signals_backlog")
        if sig["pending_fraction"] >= self.pending_headroom:
            # the scale-up-BEFORE-shed trigger: fires inside the
            # FleetOverloaded horizon, not at it
            reasons_up.append("pending")
            self._inc("up_signals_pending")
        if sig["occupancy"] >= self.hi_occupancy and sig["backlog"] > 0:
            reasons_up.append("occupancy")
            self._inc("up_signals_occupancy")
        if (self.spill_up
                and float(sig.get("spill_pressure") or 0.0)
                >= self.spill_up):
            reasons_up.append("spill")
            self._inc("up_signals_spill")

        idle = (sig["backlog"] == 0
                and sig["occupancy"] <= self.lo_occupancy
                and (p99 is None or not self.slo_p99_s
                     or p99 < self.slo_p99_s * self.slo_down_margin))

        if reasons_up:
            self._up_streak += 1
            self._down_streak = 0
        elif idle:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
            return None

        if now < self._cool_until:
            self._inc("holds_cooldown")
            return None
        if reasons_up and self._up_streak >= self.up_ticks:
            return self._act("up", sig, now, reasons=tuple(reasons_up))
        if idle and self._down_streak >= self.down_ticks:
            return self._act("down", sig, now, reasons=("idle",))
        return None

    def _act(self, direction, sig, now, reasons):
        target = sig["configured"]
        if direction == "up":
            if target >= self.max_replicas:
                self._inc("holds_bounds")
                return None
            rid = (self.fleet.add_replica() if self.role is None
                   else self.fleet.add_replica(role=self.role))
            self._inc("scale_ups")
        else:
            if target <= self.min_replicas:
                self._inc("holds_bounds")
                return None
            rid = (self.fleet.scaledown_victim() if self.role is None
                   else self.fleet.scaledown_victim(role=self.role))
            if rid is None:
                self._inc("holds_bounds")
                return None
            self.fleet.remove_replica(rid)
            self._inc("scale_downs")
        self._cool_until = now + self.cooldown_s
        self._up_streak = self._down_streak = 0
        # coherent per-process clock (ISSUE 19): tracing.now() is a wall
        # anchor + monotonic deltas, so an NTP step mid-run can never
        # reorder decisions; decisions also cite the dominant latency
        # phase so a scale-up names WHAT it is scaling for
        rec = {"action": f"scale_{direction}", "replica": rid,
               "role": self.role,
               "reasons": list(reasons), "t": tracing.now(),
               "signals": {k: sig.get(k) for k in (
                   "backlog", "pending_fraction", "occupancy", "p99_s",
                   "configured", "healthy", "dominant_phase",
                   "accepted_tokens_per_step", "spill_pressure")}}
        self.decisions.append(rec)
        self._g_target.set(target + (1 if direction == "up" else -1))
        timeline.emit(dict(rec, event="autoscale_decision"))
        return direction

    # ------------------------------------------------------------- loop
    def _run(self):
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self.interval_s)

    def start(self):
        """Start the control loop thread (idempotent)."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="fleet-autoscaler", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        """Stop the control loop (the fleet keeps its current size)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 5)
            self._thread = None

    def _inc(self, key, v=1):
        self._stats.inc(key, v)
        self._counts[key] = self._counts.get(key, 0) + v

    def stats(self):
        """THIS autoscaler's counters plus loop state (the
        process-global family — all autoscalers pooled — is
        :func:`autoscale_stats`)."""
        out = dict(self._counts)
        out.update(role=self.role,
                   min_replicas=self.min_replicas,
                   max_replicas=self.max_replicas,
                   cooldown_s=self.cooldown_s,
                   slo_p99_s=self.slo_p99_s,
                   decisions=[dict(d) for d in self.decisions])
        return out

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()
        return False
