"""Standalone inference export: serialized StableHLO + named IO.

TPU-native re-design of the reference's inference deployment surface
(ref: paddle/fluid/inference/api/analysis_predictor.cc — serialized
__model__ program + params, named input/output handles;
python/paddle/static/io.py::save_inference_model).  The reference saves a
protobuf ProgramDesc and replays IR passes at load; here the traced model
is exported as **StableHLO bytes** via ``jax.export`` with parameters baked
in, so the artifact is fully standalone: a fresh process needs no Python
class, no pickle, no source — just this file pair:

  <prefix>.stablehlo   serialized multi-platform (cpu+tpu) StableHLO
  <prefix>.pdmeta      json: input/output names, shapes, dtypes

This doubles as the interchange format the reference reaches via
``paddle.onnx.export`` (see paddle_tpu/onnx/__init__.py).
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import core
from ..framework import compile_cache as _cc
from ..framework.jax_compat import jax_export_module
from ..tensor.tensor import Tensor

# jax has re-homed the export module across releases: route through
# jax_compat (PTL001) instead of pinning a spelling
jax_export = jax_export_module()

META_SUFFIX = ".pdmeta"
HLO_SUFFIX = ".stablehlo"


def _input_avals(input_spec, symbolic=False):
    """None/-1 dims: with ``symbolic`` they become ONE shared symbolic
    dimension "b" (dynamic batch — every unknown dim is assumed to be the
    batch, which is the reference Predictor's contract too); otherwise they
    specialize to 1."""
    scope = jax_export.SymbolicScope() if symbolic else None
    avals = []
    for s in input_spec:
        if isinstance(s, (tuple, list)):
            shape, dtype = s
        else:
            shape, dtype = s.shape, s.dtype
        dt = jnp.dtype(core.convert_dtype(dtype))
        dyn = [d is None or (isinstance(d, int) and d < 0) for d in shape]
        if symbolic and any(dyn):
            spec = ", ".join("b" if isdyn else str(int(d))
                             for d, isdyn in zip(shape, dyn))
            shape = jax_export.symbolic_shape(spec, scope=scope)
        else:
            shape = tuple(1 if isdyn else int(d)
                          for d, isdyn in zip(shape, dyn))
        avals.append(jax.ShapeDtypeStruct(tuple(shape), dt))
    return avals


def save_inference_model(path_prefix, layer_or_fn, input_spec,
                         input_names=None, output_names=None,
                         platforms=("cpu", "tpu")):
    """Export ``layer_or_fn`` to a standalone artifact.

    input_spec: list of InputSpec or (shape, dtype) pairs; None/-1 dims
    export as a shared SYMBOLIC batch dimension, so one artifact serves
    any batch size (shape-polymorphic StableHLO).  Models whose ops can't
    lower polymorphically fall back to specialization at 1, recorded in
    the meta.  Parameters are baked into the program as constants.
    Returns the meta dict.
    """
    from ..nn.layer.layers import Layer
    from ..jit import functional as fx
    from ..jit.api import TracedLayer

    if isinstance(layer_or_fn, TracedLayer):
        layer_or_fn = layer_or_fn._layer or layer_or_fn._fn

    has_dynamic = any(
        any(d is None or (isinstance(d, int) and d < 0)
            for d in (s[0] if isinstance(s, (tuple, list)) else s.shape))
        for s in input_spec)
    avals = _input_avals(input_spec, symbolic=has_dynamic)
    rng = jax.random.PRNGKey(0)

    if isinstance(layer_or_fn, Layer):
        layer = layer_or_fn
        was_training = layer.training
        layer.eval()
        pv, bv = fx.param_arrays(layer)

        def pure(*arg_vals):
            out, _ = fx.functional_call(layer, pv, bv, arg_vals,
                                        rng_key=rng)
            return out
    else:
        fn = layer_or_fn
        was_training = None

        def pure(*arg_vals):
            with fx.trace_mode(rng):
                args = [Tensor(a) for a in arg_vals]
                out = fn(*args)
            return jax.tree_util.tree_map(
                lambda x: x.value if isinstance(x, Tensor) else x, out,
                is_leaf=lambda x: isinstance(x, Tensor))

    symbolic = has_dynamic
    try:
        try:
            exported = jax_export.export(jax.jit(pure),
                                         platforms=list(platforms))(*avals)
        except Exception:                               # noqa: BLE001
            if not has_dynamic:
                raise
            # some ops can't lower shape-polymorphically — specialize
            symbolic = False
            avals = _input_avals(input_spec, symbolic=False)
            exported = jax_export.export(jax.jit(pure),
                                         platforms=list(platforms))(*avals)
    finally:
        if was_training:
            layer.train()

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + HLO_SUFFIX, "wb") as f:
        f.write(exported.serialize())

    in_names = list(input_names or
                    [f"x{i}" for i in range(len(avals))])
    n_out = len(exported.out_avals)
    out_names = list(output_names or [f"out{i}" for i in range(n_out)])
    def _dims(shape):
        return [int(d) if isinstance(d, int) else -1 for d in shape]

    meta = {
        "format": "stablehlo",
        "platforms": list(platforms),
        "dynamic_batch": symbolic,
        "inputs": [{"name": n, "shape": _dims(a.shape),
                    "dtype": str(np.dtype(a.dtype))}
                   for n, a in zip(in_names, avals)],
        "outputs": [{"name": n, "shape": _dims(a.shape),
                     "dtype": str(np.dtype(a.dtype))}
                    for n, a in zip(out_names, exported.out_avals)],
    }
    with open(path_prefix + META_SUFFIX, "w") as f:
        json.dump(meta, f, indent=1)
    return meta


# the dynamic-batch pad ladder: the shared bucket maths of the unified
# compile layer (kept under the old name for existing importers)
_next_bucket = _cc.next_pow2


class StandaloneModel:
    """Loaded standalone artifact: call(*arrays) -> tuple of arrays.

    Executables are cached per input-shape SIGNATURE (dispatch.py's
    keying discipline) and counted in ``serving.standalone_compiles`` —
    repeated variable-shape calls are observable instead of silently
    retracing.  Shape-polymorphic artifacts additionally PAD their
    dynamic (batch) dims up to a power-of-two bucket and slice the
    outputs back, so nearby batch sizes share one executable: calling at
    two batch sizes inside a bucket compiles once.

    Bucketing assumes batch rows are independent (the manifest can't
    prove it: a model may mix rows yet keep the batch axis on its
    outputs, e.g. ``x - x.mean(0)``).  The first CONCLUSIVE padded call
    (one where constant- and edge-replicated pads actually differ —
    degenerate all-zero inputs prove nothing, leave the probe pending,
    and are answered at their exact shape) therefore re-runs the same
    executable under both pad modes and compares: a mismatch permanently
    disables bucketing for this instance and returns the exact result —
    never a silent wrong answer.  Models whose
    outputs drop the batch dim skip bucketing outright, and
    ``batch_bucketing=False`` opts out entirely."""

    def __init__(self, path_prefix, device=None, batch_bucketing=True):
        with open(path_prefix + HLO_SUFFIX, "rb") as f:
            hlo_bytes = f.read()
        self._exported = jax_export.deserialize(hlo_bytes)
        with open(path_prefix + META_SUFFIX) as f:
            self.meta = json.load(f)
        self._device = device
        # dynamic axes per input/output, from the -1s in the manifest
        self._in_dyn = [[ax for ax, d in enumerate(i["shape"]) if d == -1]
                        for i in self.meta["inputs"]]
        self._out_dyn = [[ax for ax, d in enumerate(o["shape"]) if d == -1]
                         for o in self.meta["outputs"]]
        # pad-to-bucket is only sound when every output carries the batch
        # dim (row-independent models): an output that AGGREGATES over
        # the batch (a scalar mean, a batch-derived dim) would absorb the
        # zero pad rows and has no axis to slice back — run those at
        # their true shape instead
        self._bucketing = bool(batch_bucketing
                               and self.meta.get("dynamic_batch")
                               and self._out_dyn
                               and all(self._out_dyn))
        from ..observability import metrics as _metrics
        self._stats = _metrics.stats_family("serving",
                                            {"standalone_compiles": 0})
        # per-shape executables live in a compile_cache site; the legacy
        # serving.standalone_compiles counter stays as the aliased view
        self._calls = _cc.site(
            "standalone", maxsize=32,
            legacy_inc=lambda ev: (self._stats.inc("standalone_compiles")
                                   if ev == "build" else None))
        # cross-process AOT identity: the artifact's own bytes (read
        # once above) + the call signature — two processes loading the
        # same <prefix>.stablehlo share serialized executables
        import hashlib as _hl
        self._hlo_digest = _hl.blake2b(hlo_bytes,
                                       digest_size=12).hexdigest()
        self._bucket_probed = False

    def input_names(self):
        return [i["name"] for i in self.meta["inputs"]]

    def output_names(self):
        return [o["name"] for o in self.meta["outputs"]]

    def _call_exact(self, arrays):
        """Run at the true input shapes (signature-cached, counted;
        AOT-serialized per shape when PADDLE_AOT_CACHE_DIR is set)."""
        sig = tuple((a.shape, str(a.dtype)) for a in arrays)
        call = self._calls.get(
            _cc.make_key(sig), lambda: jax.jit(self._exported.call),
            stable_key=f"standalone/{self._hlo_digest}/{sig}",
            example_args=tuple(arrays))
        out = call(*arrays)
        return list(out) if isinstance(out, (tuple, list)) else [out]

    def __call__(self, *arrays):
        arrays = [jnp.asarray(a) for a in arrays]
        if self._device is not None:
            arrays = [jax.device_put(a, self._device) for a in arrays]
        real_b = None
        if self._bucketing:
            for a, axes in zip(arrays, self._in_dyn):
                if axes:
                    real_b = a.shape[axes[0]]
                    break
        if real_b is None or real_b == 0 or _next_bucket(real_b) == real_b:
            # batch 0 must take the exact path too: edge-replicated pads
            # can't be built from an empty axis (and _next_bucket(0) is 1)
            return tuple(self._call_exact(arrays))

        bucket = _next_bucket(real_b)

        def pad_to_bucket(mode):
            out = []
            for a, axes in zip(arrays, self._in_dyn):
                pad = [(0, 0)] * a.ndim
                for ax in axes:
                    pad[ax] = (0, bucket - a.shape[ax])
                out.append(jnp.pad(a, pad, mode=mode) if axes else a)
            return out

        def slice_back(outs):
            sliced = []
            for o, axes in zip(outs, self._out_dyn):
                for ax in axes:
                    o = jax.lax.slice_in_dim(o, 0, real_b, axis=ax)
                sliced.append(o)
            # outputs beyond the manifest (shouldn't happen) pass through
            sliced.extend(outs[len(self._out_dyn):])
            return sliced

        padded = pad_to_bucket("constant")
        if not self._bucket_probed:
            # row-independence probe: the manifest can't tell a per-row
            # model from one that mixes rows but keeps the batch axis
            # (x - x.mean(0)).  Run the SAME executable (signature cache
            # hit, zero new compiles) under constant- AND edge-replicated
            # pads: a per-row model can't see the pads, so its real rows
            # must agree; a mismatch disables bucketing for good and
            # falls back to the exact shape — never a silent wrong answer
            alt_in = pad_to_bucket("edge")
            conclusive = any(not bool(jnp.array_equal(p, q))
                             for p, q in zip(padded, alt_in))
            if not conclusive:
                # the two pad modes built IDENTICAL inputs (the edge row
                # is all zeros), so agreement would prove nothing — leave
                # the probe pending for the next informative call and
                # serve THIS one at its exact shape, skipping the padded
                # run entirely: an unverified bucketed result could
                # silently mix pad rows into real ones
                return tuple(self._call_exact(arrays))
            sliced = slice_back(self._call_exact(padded))
            self._bucket_probed = True
            alt = slice_back(self._call_exact(alt_in))
            for s, e in zip(sliced, alt):
                if not jnp.allclose(s.astype(jnp.float32),
                                    e.astype(jnp.float32),
                                    rtol=1e-5, atol=1e-6):
                    self._bucketing = False
                    return tuple(self._call_exact(arrays))
            return tuple(sliced)
        return tuple(slice_back(self._call_exact(padded)))


def exists(path_prefix):
    return (os.path.exists(path_prefix + HLO_SUFFIX)
            and os.path.exists(path_prefix + META_SUFFIX))
