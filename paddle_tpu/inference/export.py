"""Standalone inference export: serialized StableHLO + named IO.

TPU-native re-design of the reference's inference deployment surface
(ref: paddle/fluid/inference/api/analysis_predictor.cc — serialized
__model__ program + params, named input/output handles;
python/paddle/static/io.py::save_inference_model).  The reference saves a
protobuf ProgramDesc and replays IR passes at load; here the traced model
is exported as **StableHLO bytes** via ``jax.export`` with parameters baked
in, so the artifact is fully standalone: a fresh process needs no Python
class, no pickle, no source — just this file pair:

  <prefix>.stablehlo   serialized multi-platform (cpu+tpu) StableHLO
  <prefix>.pdmeta      json: input/output names, shapes, dtypes

This doubles as the interchange format the reference reaches via
``paddle.onnx.export`` (see paddle_tpu/onnx/__init__.py).
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..framework import core
from ..tensor.tensor import Tensor

META_SUFFIX = ".pdmeta"
HLO_SUFFIX = ".stablehlo"


def _input_avals(input_spec, symbolic=False):
    """None/-1 dims: with ``symbolic`` they become ONE shared symbolic
    dimension "b" (dynamic batch — every unknown dim is assumed to be the
    batch, which is the reference Predictor's contract too); otherwise they
    specialize to 1."""
    scope = jax_export.SymbolicScope() if symbolic else None
    avals = []
    for s in input_spec:
        if isinstance(s, (tuple, list)):
            shape, dtype = s
        else:
            shape, dtype = s.shape, s.dtype
        dt = jnp.dtype(core.convert_dtype(dtype))
        dyn = [d is None or (isinstance(d, int) and d < 0) for d in shape]
        if symbolic and any(dyn):
            spec = ", ".join("b" if isdyn else str(int(d))
                             for d, isdyn in zip(shape, dyn))
            shape = jax_export.symbolic_shape(spec, scope=scope)
        else:
            shape = tuple(1 if isdyn else int(d)
                          for d, isdyn in zip(shape, dyn))
        avals.append(jax.ShapeDtypeStruct(tuple(shape), dt))
    return avals


def save_inference_model(path_prefix, layer_or_fn, input_spec,
                         input_names=None, output_names=None,
                         platforms=("cpu", "tpu")):
    """Export ``layer_or_fn`` to a standalone artifact.

    input_spec: list of InputSpec or (shape, dtype) pairs; None/-1 dims
    export as a shared SYMBOLIC batch dimension, so one artifact serves
    any batch size (shape-polymorphic StableHLO).  Models whose ops can't
    lower polymorphically fall back to specialization at 1, recorded in
    the meta.  Parameters are baked into the program as constants.
    Returns the meta dict.
    """
    from ..nn.layer.layers import Layer
    from ..jit import functional as fx
    from ..jit.api import TracedLayer

    if isinstance(layer_or_fn, TracedLayer):
        layer_or_fn = layer_or_fn._layer or layer_or_fn._fn

    has_dynamic = any(
        any(d is None or (isinstance(d, int) and d < 0)
            for d in (s[0] if isinstance(s, (tuple, list)) else s.shape))
        for s in input_spec)
    avals = _input_avals(input_spec, symbolic=has_dynamic)
    rng = jax.random.PRNGKey(0)

    if isinstance(layer_or_fn, Layer):
        layer = layer_or_fn
        was_training = layer.training
        layer.eval()
        pv, bv = fx.param_arrays(layer)

        def pure(*arg_vals):
            out, _ = fx.functional_call(layer, pv, bv, arg_vals,
                                        rng_key=rng)
            return out
    else:
        fn = layer_or_fn
        was_training = None

        def pure(*arg_vals):
            with fx.trace_mode(rng):
                args = [Tensor(a) for a in arg_vals]
                out = fn(*args)
            return jax.tree_util.tree_map(
                lambda x: x.value if isinstance(x, Tensor) else x, out,
                is_leaf=lambda x: isinstance(x, Tensor))

    symbolic = has_dynamic
    try:
        try:
            exported = jax_export.export(jax.jit(pure),
                                         platforms=list(platforms))(*avals)
        except Exception:                               # noqa: BLE001
            if not has_dynamic:
                raise
            # some ops can't lower shape-polymorphically — specialize
            symbolic = False
            avals = _input_avals(input_spec, symbolic=False)
            exported = jax_export.export(jax.jit(pure),
                                         platforms=list(platforms))(*avals)
    finally:
        if was_training:
            layer.train()

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + HLO_SUFFIX, "wb") as f:
        f.write(exported.serialize())

    in_names = list(input_names or
                    [f"x{i}" for i in range(len(avals))])
    n_out = len(exported.out_avals)
    out_names = list(output_names or [f"out{i}" for i in range(n_out)])
    def _dims(shape):
        return [int(d) if isinstance(d, int) else -1 for d in shape]

    meta = {
        "format": "stablehlo",
        "platforms": list(platforms),
        "dynamic_batch": symbolic,
        "inputs": [{"name": n, "shape": _dims(a.shape),
                    "dtype": str(np.dtype(a.dtype))}
                   for n, a in zip(in_names, avals)],
        "outputs": [{"name": n, "shape": _dims(a.shape),
                     "dtype": str(np.dtype(a.dtype))}
                    for n, a in zip(out_names, exported.out_avals)],
    }
    with open(path_prefix + META_SUFFIX, "w") as f:
        json.dump(meta, f, indent=1)
    return meta


class StandaloneModel:
    """Loaded standalone artifact: call(*arrays) -> tuple of arrays."""

    def __init__(self, path_prefix, device=None):
        with open(path_prefix + HLO_SUFFIX, "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        with open(path_prefix + META_SUFFIX) as f:
            self.meta = json.load(f)
        self._device = device
        self._call = jax.jit(self._exported.call)

    def input_names(self):
        return [i["name"] for i in self.meta["inputs"]]

    def output_names(self):
        return [o["name"] for o in self.meta["outputs"]]

    def __call__(self, *arrays):
        arrays = [jnp.asarray(a) for a in arrays]
        if self._device is not None:
            arrays = [jax.device_put(a, self._device) for a in arrays]
        out = self._call(*arrays)
        return out if isinstance(out, (tuple, list)) else (out,)


def exists(path_prefix):
    return (os.path.exists(path_prefix + HLO_SUFFIX)
            and os.path.exists(path_prefix + META_SUFFIX))
