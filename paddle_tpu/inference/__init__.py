"""Inference stack (ref: paddle/fluid/inference/): export, predictor,
serving.

* :mod:`.export` — standalone StableHLO artifacts
  (``save_inference_model`` / :class:`StandaloneModel`), with a
  per-shape-signature executable cache and dynamic-batch bucketing.
* :mod:`.predictor` — the reference-mirroring :class:`Predictor` /
  :class:`Config` / :func:`create_predictor` named-handle API over
  artifacts, jit pickles, or an in-memory Layer.
* :mod:`.serving` — :class:`ServingEngine`: continuous batching over a
  slot-pooled KV cache with bucketed prefill executables and a single
  buffer-donated decode step (ISSUE 5 tentpole).
* :mod:`.speculative` — :class:`SpeculativeServingEngine`: draft-model
  and prompt-lookup speculative decoding over the paged engine, k+1
  positions verified per donated decode step with an in-graph
  longest-accepted-prefix commit — token-exact greedy output at a
  fraction of the target forwards (ISSUE 13 tentpole).
* :mod:`.fleet` — :class:`ServingFleet`: a re-queueing router over N
  supervised engine-replica subprocesses (health checks, request
  retries, load shedding — no admitted request is ever dropped) with
  :mod:`.fleet_worker` as the replica entrypoint (ISSUE 7 tentpole).
  Elastic since ISSUE 11: ``add_replica()`` / drain-then-stop
  ``remove_replica()`` plus ``submit(priority=)`` classes.
* :mod:`.autoscale` — :class:`Autoscaler`: the SLO-driven control loop
  (queue depth, occupancy, windowed p99 vs ``PADDLE_FLEET_SLO_P99_S``)
  that scales a :class:`ServingFleet` between
  ``PADDLE_FLEET_{MIN,MAX}_REPLICAS`` with hysteresis + cooldown
  (ISSUE 11 tentpole).

Set ``PADDLE_JIT_CACHE_DIR`` to persist compiled executables across
processes: a server restart reloads them instead of re-running XLA
(framework/jax_compat.py::enable_persistent_cache).
"""
from __future__ import annotations

from . import export as export_mod                       # noqa: F401
from .export import save_inference_model, StandaloneModel  # noqa: F401
from .predictor import (Config, Predictor, create_predictor,  # noqa: F401
                        _Handle, _OutHandle)

_SERVING_NAMES = ("ServingEngine", "PagedServingEngine",
                  "ServingQueueFull", "Request")
_SPEC_NAMES = ("SpeculativeServingEngine",)
_FLEET_NAMES = ("ServingFleet", "FleetOverloaded", "FleetRequest")
_AUTOSCALE_NAMES = ("Autoscaler",)


def serving_stats():
    """Process-global ``serving.*`` counter family — a pure registry
    read, so a monitoring process can poll it WITHOUT paying the serving
    stack's GPT/Pallas import chain; empty until something serves."""
    from ..profiler import serving_stats as _serving_stats
    return _serving_stats()


def __getattr__(name):
    # serving pulls in the GPT functional core (and through it the
    # Pallas kernels) — load it on first touch, not at `import paddle_tpu`.
    # NB import_module, not `from . import serving`: the latter probes
    # this very __getattr__ for the not-yet-imported submodule and recurses
    if name in _SERVING_NAMES or name == "serving":
        import importlib
        serving = importlib.import_module(__name__ + ".serving")
        if name == "serving":
            return serving
        return getattr(serving, name)
    if name in _SPEC_NAMES or name == "speculative":
        import importlib
        speculative = importlib.import_module(__name__ + ".speculative")
        if name == "speculative":
            return speculative
        return getattr(speculative, name)
    # the fleet router is jax-light but rides the same lazy discipline
    if name in _FLEET_NAMES or name == "fleet":
        import importlib
        fleet = importlib.import_module(__name__ + ".fleet")
        if name == "fleet":
            return fleet
        return getattr(fleet, name)
    if name in _AUTOSCALE_NAMES or name == "autoscale":
        import importlib
        autoscale = importlib.import_module(__name__ + ".autoscale")
        if name == "autoscale":
            return autoscale
        return getattr(autoscale, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
