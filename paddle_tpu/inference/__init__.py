"""Inference API (ref: paddle/fluid/inference/api/paddle_inference_api.h,
python/paddle/inference/__init__.py).

TPU-native: a saved program (jit.save artifact) loads into a Predictor whose
run() is one cached XLA executable — the reference's IR pass pipeline
(fusion, memory planning) is XLA's job here.
"""
from __future__ import annotations

import numpy as np

from ..jit import api as jit_api
from ..tensor.tensor import Tensor


class Config:
    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path
        self._device = "tpu"
        self._memory_pool_mb = 0

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # accelerator

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass  # XLA always optimizes

    def set_cpu_math_library_num_threads(self, n):
        pass


class Predictor:
    def __init__(self, config):
        if isinstance(config, str):
            config = Config(config)
        path = config.model_path
        if path.endswith(jit_api._JIT_SUFFIX):
            path = path[: -len(jit_api._JIT_SUFFIX)]
        self._traced = jit_api.load(path)
        self._traced._layer.eval()
        self._inputs = []
        self._outputs = None

    def get_input_names(self):
        return [f"x{i}" for i in range(max(len(self._inputs), 1))]

    def get_input_handle(self, name):
        return _Handle(self, name)

    def get_output_names(self):
        return ["out0"]

    def get_output_handle(self, name):
        return _OutHandle(self)

    def run(self, inputs=None):
        if inputs is not None:
            self._inputs = [Tensor(np.asarray(x)) if not isinstance(x, Tensor)
                            else x for x in inputs]
        out = self._traced(*self._inputs)
        self._outputs = out if isinstance(out, (list, tuple)) else [out]
        return self._outputs


class _Handle:
    def __init__(self, predictor, name):
        self.predictor = predictor
        self.name = name

    def copy_from_cpu(self, arr):
        self.predictor._inputs.append(Tensor(np.asarray(arr)))

    def reshape(self, shape):
        pass


class _OutHandle:
    def __init__(self, predictor):
        self.predictor = predictor

    def copy_to_cpu(self):
        out = self.predictor._outputs[0]
        return out.numpy()


def create_predictor(config):
    return Predictor(config)
