"""Write-ahead request journal for the serving-fleet router (ISSUE 18).

Every zero-lost guarantee the fleet earned so far (requeue on replica
death, drain-then-stop scale-downs, handoff re-ship, host-tier
fault-back) assumed the ``ServingFleet`` router itself survives: its
pending table, parked disagg KV payloads and completion-dedupe tables
were plain in-memory dicts.  This module makes that state
reconstructible — the router appends one small record per control-plane
event and a restarted router replays them into an equivalent pending
table, then re-adopts the still-live workers (see ``fleet.py``).

Wire format — one record::

    u32 big-endian body length | 8-byte blake2b digest of body | body

where ``body`` is canonical JSON (sorted keys, no whitespace).  The
digest makes corruption DETECTABLE (a flipped byte skips one record and
counts ``journal.corrupt_records``, it never replays garbage); the
length prefix makes a torn tail TOLERABLE (a record cut short by a
crash mid-write is discarded and counted ``journal.torn_tails`` — never
a crashed recovery, because an un-acked record's request is simply
re-queued or failed NAMED by reconciliation).

Record kinds (the ``"t"`` field)::

    meta     model spec + role plan, written once per journal
    replica  rid/port/pid/role/incarnation — the adoption registry
    admit    request admission (prompt, budget, ORIGINAL wall-clock
             admit stamp so deadlines survive recovery)
    dispatch request -> replica assignment
    flip     prefill->decode phase flip; stamps the handoff payload's
             content hash + byte count + owning prefill replica — NOT
             the bytes.  Recovery re-extracts or re-prefills via the
             PR-17 fault-back path.
    requeue  preemption/displacement/incident return to the ready queue
             (carries the retry budget already burned)
    done     completion ack — tokens + finish_reason journaled so an
             at-least-once duplicate after restart still dedupes AND a
             supervised client can poll results across a router death
    fail     terminal failure with its NAMED reason
    resume   a new router generation took over this journal
    ckpt     checkpoint marker opening a compacted segment

Durability model: appends go through an UNBUFFERED file handle — every
record reaches the OS page cache immediately, so a SIGKILL of the
router process loses nothing (the kernel keeps written pages).  fsync
is batched (``PADDLE_FLEET_JOURNAL_SYNC_MS``) and only matters for
whole-host crashes; it is a justified host sync on the router control
path, never on a traced path.

Compaction: when the live segment outgrows
``PADDLE_FLEET_JOURNAL_SEGMENT_KB`` the owner (the fleet driver loop)
takes a snapshot of live state UNDER ITS OWN LOCK, releases it, and
calls :meth:`JournalWriter.compact` — which writes the snapshot into a
fresh checkpoint segment and unlinks every older segment.  Acked ids
past ``PADDLE_FLEET_DONE_RETENTION`` are dropped from the snapshot, so
the journal is bounded under sustained traffic (``journal.size_bytes``
gauge).  The one-direction call order (fleet lock -> journal lock,
never the reverse) keeps the lock graph acyclic.

Strictly stdlib (+ the stdlib-only metrics/faults modules): the router
never imports jax, and neither may its journal.
"""
from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import time

from ..observability import metrics, tracing
from ..testing import faults as _faults

_LEN = struct.Struct(">I")
_DIGEST_BYTES = 8
_HEADER = 4 + _DIGEST_BYTES
_SEG_FMT = "seg-%08d.log"
_SEG_GLOB_PREFIX = "seg-"
_SEG_SUFFIX = ".log"

# a single record larger than this is a bug, not a payload (handoff
# bytes are deliberately NOT journaled)
MAX_RECORD = 8 * 1024 * 1024


def _env_int(name, default):
    raw = os.environ.get(name)
    if raw is None or not str(raw).strip():
        return int(default)
    try:
        return int(str(raw).strip())
    except ValueError:
        return int(default)


def _stats_family():
    return metrics.stats_family("journal", {
        "appends": 0, "syncs": 0, "compactions": 0,
        "replays": 0, "replayed_records": 0,
        "corrupt_records": 0, "torn_tails": 0})


def journal_stats():
    """The process-global ``journal.*`` counter family."""
    return dict(_stats_family())


def encode_record(rec):
    """``rec`` (a JSON-able dict) -> framed bytes."""
    body = json.dumps(rec, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_RECORD:
        raise ValueError(f"journal record too large: {len(body)} bytes")
    digest = hashlib.blake2b(body, digest_size=_DIGEST_BYTES).digest()
    return _LEN.pack(len(body)) + digest + body


def payload_hash(payload):
    """Content hash of a disagg handoff payload (the wire-format dict
    of base64 arrays).  Journaled INSTEAD of the bytes: recovery only
    needs to know a payload existed and who owned it."""
    body = json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return hashlib.blake2b(body, digest_size=16).hexdigest()


def resume_submit_t(admit_wall, now_wall=None, now_perf=None):
    """Map a journaled wall-clock admit stamp back onto THIS process's
    ``perf_counter`` timeline, so a replayed request keeps its ORIGINAL
    deadline budget: time already burned before the crash stays burned
    (a near-deadline request fails ``deadline_exceeded`` after
    recovery, it does not silently restart its clock)."""
    now_wall = time.time() if now_wall is None else now_wall
    now_perf = time.perf_counter() if now_perf is None else now_perf
    return now_perf - max(0.0, now_wall - float(admit_wall))


def _iter_records(path, fam):
    """Yield intact records from one segment.  A digest mismatch skips
    that record (framing is intact, so later records still parse); a
    short read at EOF is a torn tail — discard and stop."""
    with open(path, "rb") as f:
        data = f.read()
    off, n = 0, len(data)
    while off < n:
        if off + _HEADER > n:
            fam.inc("torn_tails")
            return
        (blen,) = _LEN.unpack_from(data, off)
        if blen > MAX_RECORD:
            # a corrupted length prefix would send the frame pointer
            # into garbage — treat the rest of the segment as torn
            fam.inc("torn_tails")
            return
        end = off + _HEADER + blen
        if end > n:
            fam.inc("torn_tails")
            return
        digest = data[off + 4:off + _HEADER]
        body = data[off + _HEADER:end]
        off = end
        if hashlib.blake2b(
                body, digest_size=_DIGEST_BYTES).digest() != digest:
            fam.inc("corrupt_records")
            continue
        try:
            yield json.loads(body.decode("utf-8"))
        except ValueError:
            fam.inc("corrupt_records")


def segment_paths(dirpath):
    """Existing journal segments, oldest first."""
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    segs = sorted(n for n in names
                  if n.startswith(_SEG_GLOB_PREFIX)
                  and n.endswith(_SEG_SUFFIX))
    return [os.path.join(dirpath, n) for n in segs]


class JournalState:
    """Replayed view of a journal: the merged request table, the
    replica adoption registry, and the corruption tallies."""

    def __init__(self):
        self.meta = None
        self.replicas = {}      # rid -> {port, pid, role, incarnation}
        self.requests = {}      # id -> merged lifecycle dict
        self.order = []         # admission order
        self.records = 0
        self.resumes = 0

    def live_requests(self):
        """Admitted-but-unfinished ids, in admission order."""
        return [self.requests[i] for i in self.order
                if self.requests[i]["status"] == "pending"]

    def lost_ids(self):
        """Ids referenced by lifecycle records whose ADMIT record was
        lost to corruption and that never completed — reconciliation
        fails these NAMED (``router_recovery``), never silently.  (A
        lost admit whose ``done`` record survived is NOT lost: the
        result is intact and recovers into the done table.)"""
        return [i for i in self.order
                if self.requests[i]["rec"] is None
                and self.requests[i]["status"] != "done"]

    def _skeleton(self, rid):
        return {"id": rid, "status": "pending", "rec": None,
                "phase": None, "retries": 0, "replica": None,
                "first_token": None, "kv_hash": None, "kv_bytes": 0,
                "prefill_replica": None, "tokens": None,
                "finish_reason": None, "error": None}

    def _req(self, rid):
        r = self.requests.get(rid)
        if r is None:
            # a lifecycle record without its admit: the admit was lost
            # to corruption — keep a skeleton so later records (a
            # surviving completion especially) still merge
            r = self._skeleton(rid)
            self.requests[rid] = r
            self.order.append(rid)
        return r

    def apply(self, rec):
        self.records += 1
        t = rec.get("t")
        if t == "meta":
            self.meta = rec
        elif t == "resume":
            self.resumes += 1
        elif t == "replica":
            rid = int(rec["rid"])
            if rec.get("state") == "removed":
                self.replicas.pop(rid, None)
            else:
                self.replicas[rid] = {
                    "rid": rid, "port": int(rec["port"]),
                    "pid": int(rec.get("pid") or 0),
                    "role": rec.get("role"),
                    "incarnation": int(rec.get("incarnation", 0))}
        elif t == "admit":
            rid = rec["id"]
            r = self.requests.get(rid)
            if r is None:
                r = self._skeleton(rid)
                self.requests[rid] = r
                self.order.append(rid)
            # merge, don't replace: a checkpoint's admit may follow a
            # skeleton minted by an earlier orphan record
            r["rec"] = rec
            if r["phase"] is None:
                r["phase"] = rec.get("phase")
        elif t == "dispatch":
            r = self._req(rec["id"])
            if r is not None:
                r["replica"] = rec.get("rep")
        elif t == "flip":
            r = self._req(rec["id"])
            if r is not None:
                r["phase"] = "decode"
                r["first_token"] = rec.get("first_token")
                r["kv_hash"] = rec.get("kv_hash")
                r["kv_bytes"] = int(rec.get("kv_bytes", 0))
                r["prefill_replica"] = rec.get("prefill_replica")
                r["replica"] = None
        elif t == "requeue":
            r = self._req(rec["id"])
            if r is not None:
                r["retries"] = int(rec.get("retries", 0))
                r["replica"] = None
        elif t == "done":
            r = self._req(rec["id"])
            if r is not None:
                r["status"] = "done"
                r["tokens"] = rec.get("tokens")
                r["finish_reason"] = rec.get("finish_reason", "length")
        elif t == "fail":
            r = self._req(rec["id"])
            if r is not None:
                r["status"] = "failed"
                r["error"] = rec.get("reason", "unknown")
        # unknown kinds are forward-compatible no-ops


def replay(dirpath):
    """Read every segment into a :class:`JournalState`.  Corruption is
    counted, skipped, and NEVER raises: a torn tail or flipped byte
    yields a smaller-but-consistent state, and reconciliation handles
    the difference by re-queueing or failing named."""
    fam = _stats_family()
    corrupt0 = fam["corrupt_records"]
    torn0 = fam["torn_tails"]
    st = JournalState()
    for path in segment_paths(dirpath):
        for rec in _iter_records(path, fam):
            st.apply(rec)
    fam.inc("replays")
    fam.inc("replayed_records", st.records)
    # incident hook (ISSUE 19): journal damage files a flight dump —
    # the postmortem names the last trace hops, not just a counter
    corrupt = fam["corrupt_records"] - corrupt0
    torn = fam["torn_tails"] - torn0
    if corrupt or torn:
        tracing.dump("journal_damage",
                     extra={"dir": str(dirpath),
                            "corrupt_records": corrupt,
                            "torn_tails": torn,
                            "replayed_records": st.records})
    return st


class JournalWriter:
    """Append-only writer with batched fsync and checkpoint compaction.

    Thread-safe; the owner calls :meth:`append` from any driver thread
    (typically already holding the fleet lock — the journal lock nests
    strictly INSIDE it), and :meth:`maybe_sync` / :meth:`compact` from
    its main drive loop with the fleet lock RELEASED."""

    def __init__(self, dirpath, sync_ms=None, segment_bytes=None):
        os.makedirs(dirpath, exist_ok=True)
        self.dir = dirpath
        self.sync_ms = (_env_int("PADDLE_FLEET_JOURNAL_SYNC_MS", 50)
                        if sync_ms is None else int(sync_ms))
        self.segment_bytes = (
            _env_int("PADDLE_FLEET_JOURNAL_SEGMENT_KB", 512) * 1024
            if segment_bytes is None else int(segment_bytes))
        self._fam = _stats_family()
        self._g_size = metrics.gauge("journal.size_bytes")
        self._lock = threading.Lock()
        existing = segment_paths(dirpath)
        if existing:
            last = os.path.basename(existing[-1])
            seq = int(last[len(_SEG_GLOB_PREFIX):-len(_SEG_SUFFIX)])
            self._seq = seq  # keep appending to the newest segment
            self._total = sum(self._size_of(p) for p in existing[:-1])
        else:
            self._seq = 0
            self._total = 0
        self._f = None
        self._size = 0
        self._open_segment()
        self._unsynced = 0
        self._last_sync = time.monotonic()
        self._events = 0

    @staticmethod
    def _size_of(path):
        try:
            return os.path.getsize(path)
        except OSError:
            return 0

    def _seg_path(self, seq):
        return os.path.join(self.dir, _SEG_FMT % seq)

    def _open_segment(self):
        path = self._seg_path(self._seq)
        # buffering=0: every append is an OS write, so a SIGKILL'd
        # router loses nothing from the page cache
        self._f = open(path, "ab", buffering=0)
        self._size = self._size_of(path)

    # ------------------------------------------------------------ write
    def append(self, rec):
        """Frame + write one record.  Injectable faults:
        ``journal_corrupt_record`` flips a body byte AFTER the digest
        was stamped; ``journal_torn_write`` writes half the frame and
        hard-exits (a crash mid-write); ``router_kill:event=K``
        SIGKILLs the process after the K-th journal event."""
        buf = encode_record(rec)
        with self._lock:
            if self._f is None:
                return
            self._events += 1
            ev = self._events
            if _faults.active():
                if _faults.journal_corrupt_check():
                    # flip one byte inside the body: digest mismatch,
                    # replay must skip exactly this record
                    mid = _HEADER + max(0, (len(buf) - _HEADER) // 2)
                    buf = (buf[:mid] + bytes([buf[mid] ^ 0xFF])
                           + buf[mid + 1:])
                torn = _faults.journal_torn_write()
                if torn is not None:
                    self._f.write(buf[:max(1, len(buf) // 2)])
                    os._exit(torn)
            self._f.write(buf)
            self._size += len(buf)
            self._total += len(buf)
            self._unsynced += 1
            self._fam.inc("appends")
            self._g_size.set(self._total)
        if _faults.active():
            _faults.router_kill_check(ev)

    def maybe_sync(self, now=None):
        """Batched durability point — fsync at most once per
        ``sync_ms``.  Called from the owner's drive loop."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if (self._f is None or not self._unsynced
                    or (now - self._last_sync) * 1000.0 < self.sync_ms):
                return False
            self._fsync_locked(now)
            return True

    def sync(self):
        with self._lock:
            if self._f is not None and self._unsynced:
                self._fsync_locked(time.monotonic())

    def _fsync_locked(self, now):
        self._f.flush()
        # batched host-durability point for the router WAL; not on a
        # traced path (the router never imports jax)
        os.fsync(self._f.fileno())
        self._unsynced = 0
        self._last_sync = now
        self._fam.inc("syncs")

    # ---------------------------------------------------------- compact
    def compaction_due(self):
        with self._lock:
            return (self._f is not None
                    and self._size > self.segment_bytes)

    def compact(self, snapshot_records):
        """Write ``snapshot_records`` (the owner's full live state,
        taken under ITS lock, which is already released) into a fresh
        checkpoint segment, then unlink every older segment.  The
        journal's on-disk size collapses to the live state — acked ids
        past the owner's retention window are simply absent from the
        snapshot, so the dedupe-table footprint is bounded."""
        with self._lock:
            if self._f is None:
                return
            old = segment_paths(self.dir)
            self._seq += 1
            self._f.close()
            self._open_segment()
            self._f.write(encode_record(
                {"t": "ckpt", "n": len(snapshot_records)}))
            for rec in snapshot_records:
                self._f.write(encode_record(rec))
            self._size = self._size_of(self._seg_path(self._seq))
            self._fsync_locked(time.monotonic())
            for p in old:
                if p != self._seg_path(self._seq):
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
            self._total = self._size
            self._fam.inc("compactions")
            self._g_size.set(self._total)

    def size_bytes(self):
        with self._lock:
            return self._total

    def close(self):
        with self._lock:
            if self._f is None:
                return
            if self._unsynced:
                self._fsync_locked(time.monotonic())
            self._f.close()
            self._f = None

    def abandon(self):
        """Close the fd WITHOUT the close-time fsync: the crashed-router
        simulation (``ServingFleet._crash``).  Appends already sit in the
        OS page cache (the segment is opened unbuffered), so a SIGKILLed
        process loses nothing — only a host crash could, which is what
        the batched fsync bounds."""
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
