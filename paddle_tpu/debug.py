"""Failure detection: nan/inf guards (SURVEY.md §2.11).

TPU-native analogue of the reference's debugger / nan-inf utils (ref:
paddle/fluid/framework/details/nan_inf_utils_detail.cc, enabled there via
FLAGS_check_nan_inf): in eager mode a dispatch-level guard checks every
primitive's outputs and raises with the op name at the first non-finite
value; under jit, ``check_numerics`` embeds an XLA-side checkify-style
assert (jax.debug.check) so compiled steps fail loudly too.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

_enabled = False


def enable_check_nan_inf(flag=True):
    """Process-wide eager guard (FLAGS_check_nan_inf analogue)."""
    global _enabled
    _enabled = bool(flag)


def check_nan_inf_enabled():
    return _enabled


@contextlib.contextmanager
def check_nan_inf_guard():
    """Scoped version of enable_check_nan_inf."""
    global _enabled
    prev = _enabled
    _enabled = True
    try:
        yield
    finally:
        _enabled = prev


class NanInfError(FloatingPointError):
    pass


def _assert_finite_eager(opname, vals):
    """Called from dispatch when the guard is on; host-syncs (debug mode).
    Traced values are skipped — under jit use check_numerics instead."""
    for v in vals:
        if isinstance(v, jax.core.Tracer):
            continue
        if (hasattr(v, "dtype")
                and jnp.issubdtype(jnp.result_type(v), jnp.inexact)):
            finite = bool(jnp.all(jnp.isfinite(v)))
            if not finite:
                n_nan = int(jnp.sum(jnp.isnan(v)))
                n_inf = int(jnp.sum(jnp.isinf(v)))
                raise NanInfError(
                    f"op '{opname}' produced non-finite values "
                    f"(nan={n_nan}, inf={n_inf}, shape={tuple(v.shape)}, "
                    f"dtype={v.dtype})")


def check_numerics(tree, message="check_numerics"):
    """Jit-safe guard for compiled train steps: passes ``tree`` through
    unchanged but attaches a host callback that aborts when any floating
    leaf is non-finite (jax.debug.callback compiles into the HLO; the check
    runs device-side, only the verdict ships to host).

    Note: because the callback fires from the runtime, the failure surfaces
    at the next sync point as the backend's callback error (wrapping this
    NanInfError message), not as a typed NanInfError at the call site.  For
    a recoverable in-graph verdict (e.g. skip-step logic), use
    ``finite_mask`` instead."""
    def _raise_if(bad):
        if bad:
            raise NanInfError(message + ": non-finite value detected")

    def guard(x):
        if (hasattr(x, "dtype")
                and jnp.issubdtype(jnp.result_type(x), jnp.inexact)):
            jax.debug.callback(_raise_if, ~jnp.all(jnp.isfinite(x)))
        return x

    return jax.tree.map(guard, tree)


def finite_mask(tree):
    """Scalar bool: every floating leaf of ``tree`` is finite (the grad-
    scaler's found_inf test, usable inside jit without host sync)."""
    leaves = [x for x in jax.tree.leaves(tree)
              if hasattr(x, "dtype")
              and jnp.issubdtype(jnp.result_type(x), jnp.inexact)]
    if not leaves:
        return jnp.bool_(True)
    return jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]).all()
