"""paddle_tpu.testing — deterministic chaos/fault-injection utilities for
the fault-tolerant runtime (launcher supervision, collective watchdogs,
crash-consistent checkpointing).  Import-light: nothing here touches jax,
so workers can consult the registry before the backend exists."""
from . import faults  # noqa: F401
