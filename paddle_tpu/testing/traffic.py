"""Seeded, reproducible production-shaped serving traffic (ISSUE 11).

Real fleets are not exercised by uniform request streams: arrivals are
bursty (Poisson with a time-varying rate), rates follow a diurnal
cycle, prompt and output lengths are heavy-tailed, and a large fraction
of prompts share a common prefix (system prompts, few-shot headers —
the PR-8 prefix cache's whole reason to exist).  This module generates
that shape as plain DATA — a list of :class:`Arrival` records with
absolute arrival offsets — from one integer seed, so an autoscaling
bench or a chaos test replays the identical workload run after run.

The rate function is ``base_rate * diurnal(t) * burst(t)`` and arrivals
are drawn by Lewis thinning (candidate events at the peak rate, each
accepted with probability ``rate(t) / rate_max``), which keeps the
process exactly Poisson at every instant while staying reproducible
from a single ``numpy.random.RandomState``.

Only numpy beyond the stdlib — importable before jax, like the rest of
``paddle_tpu.testing``.
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ["Arrival", "TrafficSpec", "generate", "replay"]

PRIORITIES = ("interactive", "batch")


class Arrival:
    """One generated request: submit at ``t`` seconds after replay
    start.  ``request_id`` is stable (derived from the arrival index)
    so reruns of the same spec join on ids."""

    __slots__ = ("t", "prompt", "max_new_tokens", "priority",
                 "request_id", "prefix_hit")

    def __init__(self, t, prompt, max_new_tokens, priority, request_id,
                 prefix_hit):
        self.t = float(t)
        self.prompt = np.asarray(prompt, np.int32)
        self.max_new_tokens = int(max_new_tokens)
        self.priority = priority
        self.request_id = request_id
        self.prefix_hit = bool(prefix_hit)

    def __repr__(self):
        return (f"Arrival(t={self.t:.3f}, len={len(self.prompt)}, "
                f"new={self.max_new_tokens}, {self.priority!r}, "
                f"id={self.request_id!r})")


class TrafficSpec:
    """The knobs, one JSON-able record.

    * ``duration_s`` / ``base_rate`` — window length and the baseline
      Poisson arrival rate (requests/s).
    * ``bursts`` — ``(start_frac, end_frac, multiplier)`` phases; inside
      ``[start_frac, end_frac) * duration_s`` the rate is multiplied
      (overlapping phases compound).  The bench's "3x burst" is one
      ``(0.33, 0.66, 3.0)`` entry.
    * ``diurnal_amplitude`` — 0 disables; ``a`` modulates the rate by
      ``1 + a * sin(2*pi*t/diurnal_period_s)`` (clipped at 0), the
      slow ramp under the bursts.
    * ``prompt_len`` / ``output_tokens`` — ``(median, sigma, lo, hi)``
      log-normal draws clipped into ``[lo, hi]``: heavy-tailed like
      production token counts, but bounded so every request fits the
      engine's ladder/budget.
    * ``prefix_hit_rate`` — probability a prompt starts with one of
      ``prefix_pool`` shared ``prefix_len``-token prefixes (exercises
      PR-8 shared-prefix page reuse); the remainder of the prompt is
      unique either way.
    * ``batch_fraction`` — probability a request is ``priority="batch"``
      (the sheddable class); the rest are ``"interactive"``.
    """

    def __init__(self, duration_s=10.0, base_rate=4.0, *, seed=0,
                 vocab=256, bursts=((0.33, 0.66, 3.0),),
                 diurnal_amplitude=0.0, diurnal_period_s=None,
                 prompt_len=(5, 0.5, 3, 8), output_tokens=(12, 0.5, 4, 32),
                 prefix_hit_rate=0.0, prefix_pool=4, prefix_len=4,
                 batch_fraction=0.0, id_prefix="t"):
        self.duration_s = float(duration_s)
        self.base_rate = float(base_rate)
        self.seed = int(seed)
        self.vocab = int(vocab)
        self.bursts = tuple((float(a), float(b), float(m))
                            for a, b, m in bursts)
        self.diurnal_amplitude = float(diurnal_amplitude)
        self.diurnal_period_s = float(diurnal_period_s
                                      if diurnal_period_s is not None
                                      else duration_s)
        self.prompt_len = tuple(prompt_len)
        self.output_tokens = tuple(output_tokens)
        self.prefix_hit_rate = float(prefix_hit_rate)
        self.prefix_pool = int(prefix_pool)
        self.prefix_len = int(prefix_len)
        self.batch_fraction = float(batch_fraction)
        self.id_prefix = str(id_prefix)
        if not 0.0 <= self.batch_fraction <= 1.0:
            raise ValueError("batch_fraction must be in [0, 1]")
        if not 0.0 <= self.prefix_hit_rate <= 1.0:
            raise ValueError("prefix_hit_rate must be in [0, 1]")
        if self.prefix_hit_rate > 0 and self.prefix_len >= self.prompt_len[2]:
            # a "hit" prompt is prefix + >=1 unique tokens; a prefix at
            # or past the minimum prompt length would push hit prompts
            # beyond the promised [lo, hi] length bound
            raise ValueError(
                f"prefix_len {self.prefix_len} must be < the minimum "
                f"prompt length {self.prompt_len[2]} when "
                "prefix_hit_rate > 0")

    def rate(self, t):
        """Requests/s at offset ``t`` — the thinning target."""
        r = self.base_rate
        if self.diurnal_amplitude:
            r *= max(0.0, 1.0 + self.diurnal_amplitude
                     * np.sin(2 * np.pi * t / self.diurnal_period_s))
        for a, b, m in self.bursts:
            if a * self.duration_s <= t < b * self.duration_s:
                r *= m
        return r

    def rate_max(self):
        """An upper bound on :meth:`rate` over the window (the thinning
        envelope): peak diurnal times the product of burst multipliers
        (overlaps compound, so the product is the safe bound)."""
        r = self.base_rate * (1.0 + max(self.diurnal_amplitude, 0.0))
        for _, _, m in self.bursts:
            if m > 1.0:
                r *= m
        return r


def _clipped_lognormal(rng, median, sigma, lo, hi):
    v = rng.lognormal(mean=np.log(max(float(median), 1.0)),
                      sigma=float(sigma))
    return int(np.clip(round(v), lo, hi))


def generate(spec=None, **kw):
    """The arrival list for ``spec`` (or ``TrafficSpec(**kw)``), sorted
    by ``t``.  Same spec + seed -> byte-identical prompts, lengths,
    priorities, and arrival times."""
    if spec is None:
        spec = TrafficSpec(**kw)
    elif kw:
        raise TypeError("pass a TrafficSpec OR knobs, not both")
    rng = np.random.RandomState(spec.seed)
    # the shared-prefix pool is drawn FIRST so prefix bytes are stable
    # regardless of how many arrivals the thinning accepts
    pool = [rng.randint(1, spec.vocab, spec.prefix_len)
            for _ in range(max(spec.prefix_pool, 1))]
    rate_max = spec.rate_max()
    out = []
    t = 0.0
    i = 0
    while True:
        t += rng.exponential(1.0 / rate_max) if rate_max > 0 else spec.duration_s
        if t >= spec.duration_s:
            break
        if rng.uniform() * rate_max > spec.rate(t):
            continue                       # thinned candidate
        plen = _clipped_lognormal(rng, *spec.prompt_len)
        hit = rng.uniform() < spec.prefix_hit_rate
        if hit:
            prefix = pool[rng.randint(len(pool))]
            body = rng.randint(1, spec.vocab,
                               max(plen - spec.prefix_len, 1))
            prompt = np.concatenate([prefix, body])
        else:
            prompt = rng.randint(1, spec.vocab, plen)
        out.append(Arrival(
            t=t, prompt=prompt,
            max_new_tokens=_clipped_lognormal(rng, *spec.output_tokens),
            priority=("batch" if rng.uniform() < spec.batch_fraction
                      else "interactive"),
            request_id=f"{spec.id_prefix}{i:05d}", prefix_hit=hit))
        i += 1
    return out


def replay(arrivals, submit, *, speed=1.0, stop=None):
    """Submit each arrival at its wall-clock offset (``speed`` > 1
    compresses time).  ``submit(arrival)`` owns error handling — a
    shedding fleet raises through it and the caller decides whether a
    shed ends the run.  ``stop`` (an optional ``threading.Event``)
    aborts the replay early.  Returns the number submitted."""
    t0 = time.perf_counter()
    n = 0
    for a in arrivals:
        if stop is not None and stop.is_set():
            break
        delay = a.t / speed - (time.perf_counter() - t0)
        if delay > 0:
            if stop is not None:
                if stop.wait(delay):
                    break
            else:
                time.sleep(delay)
        submit(a)
        n += 1
    return n
