"""Clean child-interpreter environment for spawning CPU-backend worker
processes (the chaos bench, the multi-process recovery tests, any script
fanning out supervised workers on a dev box).

The container's sitecustomize initializes the axon TPU backend at
interpreter startup, so a worker that must run on the CPU backend needs
the sitecustomize PYTHONPATH entries dropped and the host-platform
device count forced BEFORE python starts.  This is the one shared
implementation of that scrub — ``bench.py``'s ``_reexec_cpu_mesh`` keeps
a private copy only because it must run before ``paddle_tpu`` (and thus
this module) can be imported.

Stdlib-only, like the rest of paddle_tpu.testing.
"""
from __future__ import annotations

import os


def clean_cpu_env(repo_root, device_count=1, base=None):
    """A child env dict: repo-first PYTHONPATH with sitecustomize entries
    dropped (other operator-provided entries kept), JAX_PLATFORMS=cpu,
    and XLA_FLAGS rewritten to force ``device_count`` host devices
    (foreign flags preserved)."""
    env = dict(base if base is not None else os.environ)
    kept = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon_site" not in p and "sitecustomize" not in p
            and p != repo_root]
    env["PYTHONPATH"] = os.pathsep.join([repo_root] + kept)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={device_count}"])
    return env
