"""Deterministic fault-injection registry (the chaos harness's control
plane; design after TorchElastic's test fixtures and Orbax's corruption
tests).

Faults are DATA, not monkeypatches: production code calls the tiny hook
functions below at its natural failure points, and the hooks are no-ops
unless a fault spec was installed — programmatically via :func:`install`
or through the ``PADDLE_FAULTS`` env var, which the launcher passes
through to workers so a supervised multi-process scenario is reproducible
from one string.

Spec grammar (``;``-separated faults, each ``kind:key=val,key=val``)::

    kill:step=4,rank=1,restart=0[,code=43]
        hard-exit (os._exit) the matching rank when its training loop
        announces step 4 of incarnation 0 — a worker dying mid-run.
    collective_delay:nth=2[,op=all_reduce][,seconds=0.5]
        sleep before contributing to the Nth matching collective (a slow
        straggler; exercises watchdog margins without killing anyone).
    collective_drop:nth=3[,op=all_reduce][,exit=41]
        hard-exit right before contributing to the Nth matching
        collective — peers see a vanished rank and must raise
        CollectiveTimeout instead of hanging.
    kv_fail:nth=2[,op=key_value_set]
        the Nth matching KV-store/coordination-service op raises a
        transient error (exercises the transport's retry-with-backoff).
    ckpt_truncate:file=model.pdparams[,step=3][,publish=1]
        truncate the matching checkpoint file to half mid-write and
        simulate the writer crashing (save aborts, tmp dir left behind,
        nothing published).  With ``publish=1`` the torn file IS
        published — a non-atomic-filesystem torn write — so restore's
        digest verify + quarantine path can be exercised end to end.

    Serving-fleet faults (consumed by inference/fleet_worker.py and the
    ServingEngine; ``rank`` here is the REPLICA id the router assigns via
    PADDLE_TRAINER_ID, ``restart`` the replica incarnation)::

    replica_kill:step=6,rank=1[,code=43]  /  replica_kill:request=5,...
        hard-exit the matching serving replica when its engine announces
        decode step N (``step=``) or admits its Nth request
        (``request=``) — a replica dying with requests in flight; the
        router must re-queue them onto survivors.
    rpc_delay:nth=2[,op=step][,seconds=0.5]
        sleep before answering the Nth matching router RPC (network
        blip / slow replica; exercises heartbeat margins).  With
        ``repeat=1`` every matching RPC is delayed — a persistently
        SLOW replica the least-loaded router should route around.
    rpc_drop:nth=3[,op=step]
        drop the reply to the Nth matching RPC (worker closes the
        connection without answering) — the router sees a vanished
        response and must retry/health-check, and any completion
        riding that reply must be re-delivered, deduped by request id.
    engine_error:step=4
        the engine's decode step N raises InjectedFault mid-step — the
        slot-leak regression path: in-flight requests must be marked
        re-queueable and their slots freed, never leaked.
    replica_slow_start:seconds=3[,rank=2[,restart=0]]
        the matching replica sleeps N seconds BEFORE building its engine
        and sending the hello — a slow-starting replica (cold page cache,
        saturated host) joining the fleet.  An autoscaler that counts a
        slow joiner as capacity too early, or an elastic router that
        wedges waiting on it, fails deterministically under this spec.
    autoscale_flap:repeat=1[,dir=up|down]
        every autoscaler tick is forced into a scale decision (with no
        ``dir`` the direction alternates fire to fire) — the control-loop
        race amplifier: min/max bounds, cooldown bookkeeping, and the
        drain-then-stop path must hold under a decision storm.  Bounds
        still apply; the fault forces the DECISION, not a bound breach.
    page_exhaustion:step=3
        the paged engine treats its decode step N as a KV page-pool
        exhaustion event: the NEWEST in-flight request must be
        preempted (pages freed, request re-queued from its prompt,
        named in telemetry/counters) — never a silent stall or loss.
    handoff_drop:nth=1[,repeat=1]
        the matching decode-phase (KV-carrying) submission to a
        serving replica is refused WITHOUT being admitted — a dropped
        prefill->decode page handoff.  The router must keep the payload
        on the pending-table entry and RE-SHIP it (zero lost, counted
        in fleet.handoff_reships).
    spec_reject:step=3[,repeat=1]
        the speculative engine's verify at decode step N is forced into
        an ALL-REJECT (accept length 0: every draft candidate refused,
        exactly one bonus token commits — the degenerate case that must
        behave like a plain decode step).  The regression it guards:
        rejected candidates must leave the paged KV pool's bytes (and
        int8 scales) byte-identical to a never-speculated run.
    host_tier_corrupt:nth=1[,repeat=1]
        flip a byte of the Nth page spilled into the host KV tier
        AFTER its content-hash stamp was taken — torn host memory / a
        bad DMA.  The engine's fault-back hash verification must
        REJECT the entry (counted in serving.fault_back_rejects) and
        fall through to a normal re-prefill; corrupted KV bytes are
        never served.
    spill_stall:nth=1[,seconds=0.2][,repeat=1]
        the Nth host-tier spill copy stalls ``seconds`` (saturated host
        memory bus / NUMA contention).  The engine must not serialize
        the donated decode dispatch behind the copy — the stall lands
        in the deferred spill-drain stage, decode latency stays flat.

    Router control-plane faults (consumed by inference/journal.py and
    fleet_worker.py; ISSUE 18)::

    router_kill:event=K
        SIGKILL the fleet ROUTER process right after its K-th journal
        event (WAL append) — the control-plane death that the
        write-ahead journal + supervisor relaunch + worker re-adoption
        must absorb with zero admitted requests lost.
    journal_torn_write:nth=K[,code=47]
        the router's K-th journal append writes only HALF the framed
        record and hard-exits — a crash mid-write.  Recovery must
        discard the torn tail (journal.torn_tails) and replay every
        intact prior record; never a crashed recovery.
    journal_corrupt_record:nth=K
        flip one body byte of the K-th journal append AFTER its digest
        was stamped — bit rot on disk.  Replay must skip exactly that
        record (journal.corrupt_records) and reconciliation must fail
        any id whose admit record was lost NAMED (``router_recovery``),
        never silently.
    readopt_timeout:[rank=R]
        the matching WORKER refuses to re-adopt after a router restart
        (exits instead of reconnecting) — the new router must treat it
        as a dead replica: incident, respawn, re-queue its claims.

Every fault fires at most once (add ``repeat=1`` to re-arm after each
fire); ``nth`` counts only calls whose other filters matched, so the Nth
occurrence is deterministic run to run.  ``rank``/``restart`` filters
read ``PADDLE_TRAINER_ID``/``PADDLE_RESTART_COUNT`` at fire time, i.e.
the identity the launcher's supervisor assigned this incarnation.

Only stdlib imports (plus the stdlib-only observability metrics
registry): the registry must be consultable before jax (and paddle_tpu
proper) are importable or initialized.
"""
from __future__ import annotations

import os
import sys
import time

from ..observability import metrics as _metrics

_registry: list[dict] = []
_env_loaded = [False]

# a VIEW over the observability registry's "faults" family (same storage)
_stats = _metrics.stats_family(
    "faults", {"faults_installed": 0, "faults_fired": 0})


class InjectedFault(RuntimeError):
    """Raised by hooks that simulate a recoverable (transient) failure."""


def fault_stats():
    return dict(_stats)


def reset_fault_stats():
    for k in _stats:
        _stats[k] = 0


# --------------------------------------------------------------- install
def _parse_one(spec):
    kind, _, body = spec.strip().partition(":")
    fault = {"kind": kind.strip()}
    if body:
        for kv in body.split(","):
            k, _, v = kv.partition("=")
            fault[k.strip()] = v.strip()
    return fault


def install(spec):
    """Install fault(s): a spec string (grammar above), a dict, or a list
    of either.  Returns the installed fault dicts."""
    if isinstance(spec, str):
        faults = [_parse_one(s) for s in spec.split(";") if s.strip()]
    elif isinstance(spec, dict):
        faults = [dict(spec)]
    else:
        faults = [dict(s) if isinstance(s, dict) else _parse_one(s)
                  for s in spec]
    for f in faults:
        f.setdefault("_matches", 0)   # calls whose filters matched
        f.setdefault("_fired", False)
        _registry.append(f)
        _stats["faults_installed"] += 1
    return faults


def clear():
    """Drop every installed fault (env specs included; they are NOT
    re-read until the next interpreter)."""
    del _registry[:]
    _env_loaded[0] = True


def _load_env():
    if not _env_loaded[0]:
        _env_loaded[0] = True
        spec = os.environ.get("PADDLE_FAULTS")
        if spec:
            install(spec)


def active():
    _load_env()
    return bool(_registry)


# ----------------------------------------------------------------- match
def _want_int(fault, key):
    v = fault.get(key)
    return None if v is None else int(v)


def take(kind, step=None, op=None, request=None, event=None):
    """The matching armed fault for this call site, or None.  A matching
    call advances the fault's occurrence counter; the fault fires (and
    disarms, unless ``repeat``) when the counter reaches ``nth``
    (default 1)."""
    _load_env()
    if not _registry:
        return None
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
    for fault in _registry:
        if fault["kind"] != kind or fault["_fired"]:
            continue
        if _want_int(fault, "rank") is not None \
                and _want_int(fault, "rank") != rank:
            continue
        if _want_int(fault, "restart") is not None \
                and _want_int(fault, "restart") != restart:
            continue
        if _want_int(fault, "step") is not None \
                and _want_int(fault, "step") != step:
            # a step-scoped fault never matches a call site that has no
            # step notion (step=None) — firing "at the first occurrence"
            # instead would silently corrupt the chaos scenario
            continue
        if _want_int(fault, "request") is not None \
                and _want_int(fault, "request") != request:
            # same contract as step= for request-count-scoped faults
            continue
        if _want_int(fault, "event") is not None \
                and _want_int(fault, "event") != event:
            # same contract again for journal-event-scoped faults
            continue
        want_op = fault.get("op") or fault.get("file")
        if want_op and want_op not in str(op or ""):
            continue
        fault["_matches"] += 1
        if fault["_matches"] != (_want_int(fault, "nth") or 1):
            continue
        if not int(fault.get("repeat", 0)):
            fault["_fired"] = True
        else:
            fault["_matches"] = 0
        _stats["faults_fired"] += 1
        # chaos visibility (ISSUE 19): every firing leaves a trace
        # event, so an assembled lifecycle shows WHICH injected fault
        # bent it.  Lazy import: the registry must stay consultable
        # before the observability package finishes importing.
        try:
            from ..observability import tracing as _tracing
            _tracing.event("fault_fired", kind=kind, op=op, step=step,
                           request=request, jevent=event,
                           nth=_want_int(fault, "nth") or 1)
        except Exception:                                  # noqa: BLE001
            pass
        return fault
    return None


# ----------------------------------------------------------------- hooks
def kill_check(step):
    """Training loops call this once per step; a matching ``kill`` fault
    hard-exits the process (the supervisor sees a failed worker)."""
    fault = take("kill", step=step)
    if fault is not None:
        code = int(fault.get("code", 43))
        print(f"# faults: kill at step {step} (exit {code})",
              file=sys.stderr, flush=True)
        os._exit(code)


def collective_entry(op):
    """Called by the eager collective transport before contributing.
    ``collective_delay`` sleeps; ``collective_drop`` hard-exits (a rank
    vanishing mid-rendezvous)."""
    fault = take("collective_delay", op=op)
    if fault is not None:
        time.sleep(float(fault.get("seconds", 0.5)))
    fault = take("collective_drop", op=op)
    if fault is not None:
        code = int(fault.get("exit", 41))
        print(f"# faults: dropping collective '{op}' (exit {code})",
              file=sys.stderr, flush=True)
        os._exit(code)


def kv_fault(op):
    """Called per KV-store op; a matching ``kv_fail`` raises a transient
    InjectedFault the transport's retry loop must absorb."""
    fault = take("kv_fail", op=op)
    if fault is not None:
        raise InjectedFault(f"injected transient kv failure on '{op}'")


def checkpoint_truncate(step, file):
    """The ``ckpt_truncate`` fault spec matching this save, or None.  The
    checkpoint writer truncates the file and (unless ``publish=1``)
    simulates the writer crashing before the atomic rename."""
    return take("ckpt_truncate", step=step, op=file)


# ------------------------------------------------------- serving faults
def replica_kill_check(step=None, request=None):
    """Serving replicas call this per engine step (``step=``) and per
    admitted request (``request=``); a matching ``replica_kill`` fault
    hard-exits the replica — the router sees a dead worker with requests
    in flight and must re-queue them."""
    fault = take("replica_kill", step=step, request=request)
    if fault is not None:
        code = int(fault.get("code", 43))
        where = (f"step {step}" if step is not None
                 else f"request {request}")
        print(f"# faults: replica kill at {where} (exit {code})",
              file=sys.stderr, flush=True)
        os._exit(code)


def rpc_entry(op):
    """Called by the fleet worker's RPC server per incoming message.
    ``rpc_delay`` sleeps before the reply (slow replica / network blip);
    a matching ``rpc_drop`` returns True — the caller must close the
    connection WITHOUT replying, so the router exercises its
    retry/health path and completion dedupe."""
    fault = take("rpc_delay", op=op)
    if fault is not None:
        time.sleep(float(fault.get("seconds", 0.5)))
    return take("rpc_drop", op=op) is not None


def handoff_drop():
    """Called by the fleet worker per incoming decode-phase
    (KV-carrying) submission; returns True when a matching
    ``handoff_drop`` fault fires — the worker must refuse the item
    WITHOUT admitting it, so the router re-ships the pages from the
    pending-table entry (retry re-ships, zero lost)."""
    return take("handoff_drop") is not None


def page_exhaustion_check(step=None):
    """Called by the paged serving engine once per decode step; returns
    True when a matching ``page_exhaustion`` fault fires — the engine
    must run its real exhaustion path (preempt the newest request back
    to the queue, pages freed, failure named) without the pool actually
    being full."""
    return take("page_exhaustion", step=step) is not None


def spec_reject_check(step=None):
    """Called by the speculative serving engine once per verify step;
    returns True when a matching ``spec_reject`` fault fires — the
    engine must force an all-reject verify (commit exactly the one
    bonus token) while leaving paged KV bytes exactly as a
    never-speculated run would."""
    return take("spec_reject", step=step) is not None


def slow_start_check():
    """Fleet replicas call this once at boot, before building the engine
    and sending the router hello; a matching ``replica_slow_start``
    fault sleeps ``seconds`` — a deterministically slow joiner for
    elastic-fleet / autoscaler races."""
    fault = take("replica_slow_start")
    if fault is not None:
        s = float(fault.get("seconds", 1.0))
        print(f"# faults: replica slow start, sleeping {s}s before hello",
              file=sys.stderr, flush=True)
        time.sleep(s)


def autoscale_flap():
    """Called by the autoscaler once per control tick; returns a forced
    scale direction (``"up"``/``"down"``) when a matching
    ``autoscale_flap`` fault fires, else None.  With no ``dir=`` the
    direction alternates across fires (install with ``repeat=1`` to
    force a decision EVERY tick)."""
    fault = take("autoscale_flap")
    if fault is None:
        return None
    d = fault.get("dir")
    if d in ("up", "down"):
        return d
    fault["_flap_up"] = not fault.get("_flap_up", False)
    return "up" if fault["_flap_up"] else "down"


def host_tier_corrupt():
    """Called by the paged engine once per page spilled into the host
    KV tier (after the hash stamp); returns True when a matching
    ``host_tier_corrupt`` fault fires — the engine must flip a stored
    byte so the fault-back verification exercises its reject path
    (fall back to re-prefill; never serve bad KV)."""
    return take("host_tier_corrupt") is not None


def spill_stall():
    """Called by the paged engine's deferred spill-drain stage once per
    host-tier copy; returns the injected stall seconds when a matching
    ``spill_stall`` fault fires, else None.  The decode dispatch must
    already have been issued — the stall pins that host copies never
    serialize the decode step."""
    fault = take("spill_stall")
    if fault is None:
        return None
    return float(fault.get("seconds", 0.2))


# -------------------------------------------------- control-plane faults
def router_kill_check(event):
    """The router's journal writer calls this once per appended WAL
    record; a matching ``router_kill`` fault SIGKILLs the router
    process at journal event K — no atexit, no cleanup, workers
    orphaned alive.  The supervisor + journal replay + worker
    re-adoption must recover with zero admitted requests lost."""
    fault = take("router_kill", event=event)
    if fault is not None:
        print(f"# faults: router SIGKILL at journal event {event}",
              file=sys.stderr, flush=True)
        os.kill(os.getpid(), 9)


def journal_torn_write():
    """Called by the journal writer per append; returns the hard-exit
    code when a matching ``journal_torn_write`` fault fires, else None
    — the writer must emit HALF the framed record then ``os._exit``
    (a crash mid-write, leaving a torn tail for replay to discard)."""
    fault = take("journal_torn_write")
    if fault is None:
        return None
    return int(fault.get("code", 47))


def journal_corrupt_check():
    """Called by the journal writer per append; returns True when a
    matching ``journal_corrupt_record`` fault fires — the writer flips
    one body byte AFTER the digest stamp, so replay's digest check must
    skip exactly that record and count ``journal.corrupt_records``."""
    return take("journal_corrupt_record") is not None


def readopt_refused():
    """Called by a fleet worker when its router connection dies and a
    re-adoption window is configured; returns True when a matching
    ``readopt_timeout`` fault fires — the worker exits instead of
    reconnecting, and the restarted router must treat it as dead
    (incident -> respawn -> re-queue its claimed requests)."""
    return take("readopt_timeout") is not None


def engine_step_error(step):
    """Called by ServingEngine.step() before the decode dispatch; a
    matching ``engine_error`` fault raises InjectedFault mid-step — the
    slot-leak regression path (in-flight requests must be freed and
    marked re-queueable, not leaked)."""
    fault = take("engine_error", step=step)
    if fault is not None:
        raise InjectedFault(
            f"injected serving engine error at decode step {step}")
