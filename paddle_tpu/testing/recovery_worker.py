"""Deterministic training worker for the fault-tolerance e2e harness
(bench.py --faults, tools/chaos_smoke.sh, tests/test_fault_tolerance.py).

Runs a tiny fixed-seed regression under the supervised launcher:

* multi-process groups train data-parallel through the bucketed reducer's
  eager cross-process transport, with every rank fed the SAME per-step
  batch (derived from the step index) — the averaged gradient then equals
  the local gradient bit-for-bit, so an uninterrupted single-process run
  is an exact parity reference for the recovered run;
* rank 0 checkpoints every step through the async CheckpointManager;
* each step announces itself to the fault registry
  (``faults.kill_check``), so a ``PADDLE_FAULTS=kill:step=K,...`` spec
  makes a worker die mid-run exactly once;
* on relaunch (PADDLE_RESTART_COUNT > 0) every rank restores from the
  last PUBLISHED checkpoint and writes a ``resumed_<incarnation>``
  marker (wall-clock + resumed step) the harness uses to measure
  time-to-recover;
* at the end each rank dumps its parameters to ``params_rank<r>.npz``.

Usage (always under the launcher, which sets the PADDLE_* env):
    python -m paddle_tpu.testing.recovery_worker \
        --ckpt DIR --out DIR --steps N [--width W] [--lr LR]
"""
from __future__ import annotations

import argparse
import json
import os
import time


def main(argv=None):
    parser = argparse.ArgumentParser("paddle_tpu.testing.recovery_worker")
    parser.add_argument("--ckpt", required=True,
                        help="shared checkpoint directory")
    parser.add_argument("--out", required=True,
                        help="output directory (markers + final params)")
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--width", type=int, default=16)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--sync-ckpt", action="store_true",
                        help="blocking saves (default: async)")
    args = parser.parse_args(argv)

    import numpy as np
    import paddle_tpu as paddle            # bootstraps jax.distributed
    import paddle_tpu.distributed as dist
    from paddle_tpu.testing import faults
    from paddle_tpu.utils import CheckpointManager

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
    os.makedirs(args.out, exist_ok=True)

    paddle.seed(1234)                      # identical init on every rank
    net = paddle.nn.Sequential(
        paddle.nn.Linear(args.width, args.width), paddle.nn.Tanh(),
        paddle.nn.Linear(args.width, 4))
    opt = paddle.optimizer.Momentum(args.lr, parameters=net.parameters())
    model = dist.DataParallel(net) if nprocs > 1 else net

    mgr = CheckpointManager(args.ckpt, keep=2,
                            async_save=not args.sync_ckpt)
    start = mgr.restore(model=net, optimizer=opt) or 0
    if restart > 0:
        with open(os.path.join(args.out, f"resumed_{restart}_r{rank}"),
                  "w") as f:
            json.dump({"time": time.time(), "resumed_step": start,
                       "rank": rank}, f)

    # step-timeline telemetry: per-step records land in the JSONL event
    # log when the launcher set PADDLE_TELEMETRY_DIR (the harness merges
    # them into the cross-rank report)
    from paddle_tpu.observability import StepTimer
    with StepTimer(name="recovery_worker", start_step=start) as timer:
        for step in range(start + 1, args.steps + 1):
            faults.kill_check(step)        # chaos: die here if told to
            rng = np.random.RandomState(9000 + step)  # same data each rank
            x = paddle.to_tensor(rng.randn(8, args.width)
                                 .astype(np.float32))
            y = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
            with timer.step():
                loss = paddle.nn.functional.mse_loss(model(x), y)
                loss.backward()
                opt.step()
                opt.clear_grad()
            if rank == 0:
                mgr.save(step, model=net, optimizer=opt)
    mgr.wait()                             # all checkpoints published

    np.savez(os.path.join(args.out, f"params_rank{rank}.npz"),
             **{f"p{i}": np.asarray(p.numpy())
                for i, p in enumerate(net.parameters())})
    with open(os.path.join(args.out, f"done_r{rank}"), "w") as f:
        f.write(str(time.time()))


if __name__ == "__main__":
    main()
