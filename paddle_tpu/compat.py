"""paddle.compat (ref: python/paddle/compat.py) — py2/py3 text helpers
the fluid era shipped; still imported by reference-era utilities."""
from __future__ import annotations

__all__ = ["to_text", "to_bytes", "long_type", "get_exception_message",
           "floor_division", "round"]

import builtins
import math

long_type = int


def _to_text(obj, encoding):
    if obj is None:
        return obj
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    if isinstance(obj, str):
        return obj
    return str(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_to_text(o, encoding) for o in obj]
            return obj
        return [_to_text(o, encoding) for o in obj]
    if isinstance(obj, set):
        if inplace:
            vals = [_to_text(o, encoding) for o in obj]
            obj.clear()
            obj.update(vals)
            return obj
        return {_to_text(o, encoding) for o in obj}
    return _to_text(obj, encoding)


def _to_bytes(obj, encoding):
    if obj is None:
        return obj
    if isinstance(obj, str):
        return obj.encode(encoding)
    if isinstance(obj, bytes):
        return obj
    return str(obj).encode(encoding)


def to_bytes(obj, encoding="utf-8", inplace=False):
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_to_bytes(o, encoding) for o in obj]
            return obj
        return [_to_bytes(o, encoding) for o in obj]
    if isinstance(obj, set):
        if inplace:
            vals = [_to_bytes(o, encoding) for o in obj]
            obj.clear()
            obj.update(vals)
            return obj
        return {_to_bytes(o, encoding) for o in obj}
    return _to_bytes(obj, encoding)


def get_exception_message(exc):
    return str(exc)


def floor_division(x, y):
    return x // y


def round(x, d=0):                          # noqa: A001
    return builtins.round(x, d) if d else float(math.floor(x + 0.5)) \
        if x >= 0 else float(math.ceil(x - 0.5))
