"""Concrete optimizers (ref: python/paddle/optimizer/{sgd,momentum,adam,...}.py
and the corresponding fluid/operators/optimizers/*_op kernels — here each is a
pure jax update rule; XLA fuses the whole step).
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


def _bias_correction(beta, t):
    """``1 - beta**t`` computed in f32 ON DEVICE for both the eager loop
    (python-int t) and compiled steps (traced t): identical arithmetic is
    what makes fused-vs-eager parity bitwise-tight instead of drifting a
    ulp per step through the nonlinearity."""
    return 1.0 - jnp.power(jnp.float32(beta), jnp.asarray(t, jnp.float32))


class SGD(Optimizer):
    _accum_names = ()

    def _update(self, p, g, state, lr, t=1):
        return p - lr * g.astype(p.dtype), {}


class Momentum(Optimizer):
    _accum_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update(self, p, g, state, lr, t=1):
        g = g.astype(p.dtype)
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adagrad(Optimizer):
    _accum_names = ("moment",)

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_val = initial_accumulator_value

    def _init_accumulator(self, name, p):
        return jnp.full_like(p.value, self._init_val)

    def _update(self, p, g, state, lr, t=1):
        g = g.astype(p.dtype)
        m = state["moment"] + g * g
        new_p = p - lr * g / (jnp.sqrt(m) + self._epsilon)
        return new_p, {"moment": m}


class Adadelta(Optimizer):
    _accum_names = ("avg_squared_grad", "avg_squared_update")

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._rho = rho

    def _update(self, p, g, state, lr, t=1):
        g = g.astype(p.dtype)
        eg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * g * g
        dx = (jnp.sqrt(state["avg_squared_update"] + self._epsilon)
              / jnp.sqrt(eg + self._epsilon)) * g
        eu = self._rho * state["avg_squared_update"] + (1 - self._rho) * dx * dx
        return p - lr * dx, {"avg_squared_grad": eg, "avg_squared_update": eu}


class Adam(Optimizer):
    _accum_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        # Tensor betas are kept LIVE (ref: warmup schedules update the
        # beta Variable in place); eager steps read the current value
        # each step.  Compiled steps snapshot at trace time.
        self._beta1_src = beta1
        self._beta2_src = beta2
        self._epsilon = (float(epsilon.numpy())
                         if hasattr(epsilon, "numpy") else epsilon)

    @property
    def _beta1(self):
        b = self._beta1_src
        return float(b.numpy()) if hasattr(b, "numpy") else b

    @property
    def _beta2(self):
        b = self._beta2_src
        return float(b.numpy()) if hasattr(b, "numpy") else b

    def _fused_hyper_token(self):
        # Tensor betas are LIVE (warmup schedules mutate them in place):
        # bake the CURRENT values into the fused-step signature so an
        # in-place update forces a retrace instead of replaying stale
        # constants
        return super()._fused_hyper_token() + (self._beta1, self._beta2)

    def _update(self, p, g, state, lr, t=1):
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * gf
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * gf * gf
        mhat = m / _bias_correction(self._beta1, t)
        vhat = v / _bias_correction(self._beta2, t)
        new_p = pf - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}

    def _init_accumulator(self, name, p):
        return jnp.zeros(p.value.shape, jnp.float32)


class AdamW(Adam):
    """Decoupled weight decay (ref: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip)
        # numeric 0 (incl. the int spelling) must DISABLE decay — only an
        # omitted value falls back to the reference default 0.01
        if weight_decay is None:
            self._coeff = 0.01
        elif isinstance(weight_decay, (int, float)):
            self._coeff = float(weight_decay)
        else:
            self._coeff = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decay_term(self, p, pv):
        return None  # decoupled: applied inside _update

    def _update(self, p, g, state, lr, t=1):
        decay = self._coeff
        pf = p.astype(jnp.float32)
        new_p, new_state = super()._update(p, g, state, lr, t)
        if decay:
            new_p = new_p.astype(jnp.float32) - lr * decay * pf
        return new_p.astype(p.dtype), new_state

    def _update_with_param(self, p, pv, g, state, lr, t):
        # per-param decay coefficient: apply_decay_param_fun exclusion
        # and per-group weight_decay overrides (optimizer.py _param_wd),
        # honored identically on the eager and compiled paths
        coeff = self._coeff
        if p is not None:
            if (self._apply_decay_param_fun is not None
                    and not self._apply_decay_param_fun(p.name)):
                coeff = 0.0
            elif id(p) in self._param_wd:
                wd = self._param_wd[id(p)]
                coeff = float(wd) if isinstance(wd, (int, float)) else wd
        if coeff != self._coeff:
            saved, self._coeff = self._coeff, coeff
            try:
                return self._update(pv, g, state, lr, t)
            finally:
                self._coeff = saved
        return self._update(pv, g, state, lr, t)


class Adamax(Optimizer):
    _accum_names = ("moment", "inf_norm")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update(self, p, g, state, lr, t=1):
        g = g.astype(p.dtype)
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g))
        new_p = p - (lr / _bias_correction(self._beta1, t)) * m \
            / (u + self._epsilon)
        return new_p, {"moment": m, "inf_norm": u}


class RMSProp(Optimizer):
    _accum_names = ("mean_square", "mean_grad", "momentum_acc")

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update(self, p, g, state, lr, t=1):
        g = g.astype(p.dtype)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum_acc"] + lr * g / denom
        return p - mom, {"mean_square": ms, "mean_grad": mg,
                         "momentum_acc": mom}


class Lamb(Optimizer):
    _accum_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_accumulator(self, name, p):
        return jnp.zeros(p.value.shape, jnp.float32)

    def _update_with_param(self, p, pv, g, state, lr, t):
        # the LAMB recipe excludes norm/bias params from decay via
        # exclude_from_weight_decay_fn; per-group weight_decay overrides
        # apply too — honored on both step paths
        wd = self._lamb_wd
        if p is not None:
            if self._exclude_fn is not None and self._exclude_fn(p):
                wd = 0.0
            elif id(p) in self._param_wd:
                ov = self._param_wd[id(p)]
                wd = float(ov) if isinstance(ov, (int, float)) else wd
        if wd != self._lamb_wd:
            saved, self._lamb_wd = self._lamb_wd, wd
            try:
                return self._update(pv, g, state, lr, t)
            finally:
                self._lamb_wd = saved
        return self._update(pv, g, state, lr, t)

    def _update(self, p, g, state, lr, t=1):
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * gf
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * gf * gf
        mhat = m / _bias_correction(self._beta1, t)
        vhat = v / _bias_correction(self._beta2, t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon)
        update = r + self._lamb_wd * pf
        w_norm = jnp.linalg.norm(pf)
        u_norm = jnp.linalg.norm(update)
        ratio = jnp.where(w_norm > 0,
                          jnp.where(u_norm > 0, w_norm / u_norm, 1.0), 1.0)
        new_p = pf - lr * ratio * update
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}


class DecayedAdagrad(Optimizer):
    """ref fluid/optimizer.py::DecayedAdagradOptimizer — adagrad with an
    exponentially decayed accumulator."""
    _accum_names = ("moment",)

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._decay = decay
        self._epsilon = epsilon

    def _update(self, p, g, state, lr, t=1):
        g = g.astype(p.dtype)
        m = self._decay * state["moment"] + (1 - self._decay) * g * g
        return p - lr * g / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class Ftrl(Optimizer):
    """ref fluid/optimizer.py::FtrlOptimizer (FTRL-proximal, McMahan 2013):
    per-coordinate adaptive rates with L1/L2 proximal shrinkage — the CTR
    workhorse next to the sparse-embedding models."""
    _accum_names = ("squared", "linear")

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _update(self, p, g, state, lr, t=1):
        g = g.astype(p.dtype)
        n, z = state["squared"], state["linear"]
        n_new = n + g * g
        sigma = (n_new ** (-self._lr_power)
                 - n ** (-self._lr_power)) / lr
        z_new = z + g - sigma * p
        new_p = jnp.where(
            jnp.abs(z_new) <= self._l1,
            jnp.zeros_like(p),
            (jnp.sign(z_new) * self._l1 - z_new)
            / (n_new ** (-self._lr_power) / lr + 2 * self._l2))
        return new_p, {"squared": n_new, "linear": z_new}


class Dpsgd(Optimizer):
    """ref fluid/optimizer.py::DpsgdOptimizer — differentially private SGD:
    per-update clipping + gaussian noise (Abadi et al. 2016)."""
    _accum_names = ()
    # the per-parameter noise stream is keyed on the param OBJECT identity
    # (id(p) inside _update): compiling once and replaying would freeze
    # the fold — keep DP-SGD on the per-parameter eager path
    _fused_supported = False

    def __init__(self, learning_rate=0.001, clip=0.9, batch_size=0.999,
                 sigma=1.0, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, seed=0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._clip = clip
        self._batch = batch_size
        self._sigma = sigma
        self._seed = seed

    def _update(self, p, g, state, lr, t=1):
        import jax
        g = g.astype(p.dtype)
        norm = jnp.sqrt(jnp.sum(g * g))
        g = g / jnp.maximum(1.0, norm / self._clip)
        key = jax.random.fold_in(jax.random.PRNGKey(self._seed),
                                 jnp.asarray(t, jnp.int32))
        # per-PARAMETER stream: params of equal size must not share noise
        # (independence is what the DP accounting assumes)
        key = jax.random.fold_in(key, id(p) % (2**31 - 1))
        noise = jax.random.normal(key, g.shape, jnp.float32) \
            * (self._sigma * self._clip / self._batch)
        return p - lr * (g + noise.astype(p.dtype)), {}


class LarsMomentum(Momentum):
    """ref fluid/optimizer.py::LarsMomentumOptimizer (You et al. 2017):
    layer-wise adaptive rate scaling — local lr = coeff * ||w|| /
    (||g|| + lambda * ||w||), then momentum."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, momentum, parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip)
        self._coeff = lars_coeff
        self._lwd = lars_weight_decay

    def _update(self, p, g, state, lr, t=1):
        # reference lars_momentum_op: local_lr = lr * coeff * ||w|| /
        # (||g|| + lambda * ||w|| + eps); zero-norm params get zero local
        # lr (exclude biases from LARS param lists, as the reference does)
        g = g.astype(p.dtype)
        wn = jnp.sqrt(jnp.sum(p * p))
        gn = jnp.sqrt(jnp.sum(g * g))
        local = self._coeff * wn / (gn + self._lwd * wn + 1e-12)
        g_eff = g + self._lwd * p
        v = self._momentum * state["velocity"] + lr * local * g_eff
        return p - v, {"velocity": v}
