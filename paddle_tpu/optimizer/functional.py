"""Functional optimizer updates shared by the jitted model train steps.

The eager ``paddle_tpu.optimizer.AdamW`` class (optimizer/optimizers.py)
serves the dygraph API; the model families' compiled train steps
(models/gpt_hybrid.py, models/bert.py, ...) inline this pure function so the
whole update fuses into the one XLA step program (ref: the reference fuses
its update into adamw_op.cu for the same reason)."""
from __future__ import annotations

import jax.numpy as jnp


def adamw_update(p, g, m, v, lr, t, b1, b2, eps, wd, decay):
    """One fused AdamW step in fp32 master precision.

    p: param leaf (any dtype; updated in fp32, cast back), g: grad,
    m/v: moments (math always runs fp32; stored back in their own dtype,
    so bf16 moments halve optimizer-state HBM on big models), t: fp32
    1-based step count, decay: bool — apply weight decay to this leaf.
    Returns (new_p, new_m, new_v)."""
    mdt, vdt = m.dtype, v.dtype
    gf = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    m = b1 * m.astype(jnp.float32) + (1 - b1) * gf
    v = b2 * v.astype(jnp.float32) + (1 - b2) * gf * gf
    mhat = m / (1 - b1 ** t)
    vhat = v / (1 - b2 ** t)
    upd = mhat / (jnp.sqrt(vhat) + eps) + (wd * pf if decay else 0.0)
    return (pf - lr * upd).astype(p.dtype), m.astype(mdt), v.astype(vdt)
