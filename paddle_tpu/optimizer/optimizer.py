"""Optimizer base (ref: python/paddle/optimizer/optimizer.py).

Each concrete optimizer defines a PURE update rule
``_update(p, g, state, lr) -> (new_p, new_state)`` over jax arrays.  Eager
``step()`` applies it per-parameter; the jitted train-step path (hapi/jit)
reuses the same rule inside one compiled function so the whole update fuses
into the step's HLO — the reference instead launches one CUDA kernel per op.

Fused eager step: the classic eager ``step()`` loop issues O(num_params)
tiny XLA dispatches — on TPU that host overhead, not compute, dominates.
``_apply_gradients`` therefore stacks all (param, grad, accumulator)
triples into one pytree and applies ``_update_with_param`` for every
parameter under a SINGLE ``jax.jit`` call with params and moments donated
(buffers update in place on device) — one XLA dispatch per step regardless
of parameter count.  The compiled executable is cached per abstract
signature (param/grad/moment avals + hyperparameters + per-param lr/decay
metadata); anything the signature can't soundly describe falls back to the
per-parameter eager loop.  Knobs: ``PADDLE_TPU_FUSED_STEP=0`` disables,
``PADDLE_TPU_FUSED_DONATE=0/1/auto`` controls donation (auto: off on CPU,
where XLA ignores donation anyway).
"""
from __future__ import annotations

import collections
import os

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import core
from ..observability import metrics as _metrics
from ..observability import timeline as _timeline
from ..tensor.tensor import Tensor, Parameter
from .lr import LRScheduler

# fused-step counters, surfaced through paddle_tpu.profiler; a VIEW over
# the observability registry's "fused_step" family (same storage)
_fused_stats = _metrics.stats_family(
    "fused_step", {"calls": 0, "compiles": 0, "eager_steps": 0})


def reset_fused_stats():
    _fused_stats.update(calls=0, compiles=0, eager_steps=0)


def _donation_enabled():
    from ..framework import jax_compat
    return jax_compat.donation_enabled("PADDLE_TPU_FUSED_DONATE")


class _UnhashableSignature(Exception):
    """Fused-step signature had an unhashable component (possibly
    transient metadata) — retry next step instead of permanently
    disabling the fused path."""




def _meta_token(v):
    """Hashable token for optimizer/param metadata that the fused trace
    bakes in.  Objects (regularizers, callables) are returned verbatim —
    identity-keyed, and the cache key then pins them alive so ids cannot
    be reused."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return v      # identity-hashed object; key tuple keeps the reference


class Optimizer:
    _accum_names: tuple = ()
    # optimizers whose update rule cannot be soundly compiled once and
    # replayed (e.g. param-identity-dependent RNG) opt out
    _fused_supported = True

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None else []
        self._grad_clip = grad_clip
        self._weight_decay = weight_decay
        self._accumulators = collections.defaultdict(dict)  # name -> {pid: arr}
        self._step_count = 0
        self._param_groups = None
        # signature -> jitted compiled step: a compile_cache site (the
        # unified compile layer); fused_step.compiles stays the aliased
        # legacy view, fed by the site's build events
        from ..framework import compile_cache as _cc
        self._fused_cache = _cc.site(
            "fused_step", maxsize=8,
            legacy_inc=lambda ev: (_fused_stats.inc("compiles")
                                   if ev == "build" else None))
        self._fused_mutating = False
        self._param_wd = {}       # id(p) -> per-group weight_decay override
        if (self._parameters and isinstance(self._parameters[0], dict)):
            self._param_groups = self._parameters
            self._parameters = []
            for g in self._param_groups:
                ps = list(g["params"])
                self._parameters.extend(ps)
                # per-group options ride the per-param mechanisms: the
                # group lr is a multiplier on the base lr (the ParamAttr
                # convention, ref optimizer.py:449 optimize_attr) and
                # weight_decay overrides the global one for these params
                for p in ps:
                    if "learning_rate" in g and isinstance(p, Parameter):
                        p.optimize_attr["learning_rate"] = float(
                            g["learning_rate"])
                    if "weight_decay" in g:
                        self._param_wd[id(p)] = g["weight_decay"]

    # ------------------------------------------------------------------ lr
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the lr is an LRScheduler instance")
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # ---------------------------------------------------------------- state
    def _state_for(self, p):
        key = id(p)
        states = {}
        for nm in self._accum_names:
            if key not in self._accumulators[nm]:
                self._accumulators[nm][key] = self._init_accumulator(nm, p)
            states[nm] = self._accumulators[nm][key]
        return states

    def _init_accumulator(self, name, p):
        zeros = jnp.zeros_like(p.value)
        place = getattr(self, "_accumulator_placement", None)
        if place is not None:      # ZeRO: dp-sharded moment placement
            zeros = place(p, zeros)
        return zeros

    def _update(self, p, g, state, lr, t=1):
        """Pure update rule.  ``t`` is the 1-based step count (python int
        eagerly, traced scalar under jit so bias correction doesn't force
        retraces)."""
        raise NotImplementedError

    # ---------------------------------------------------------------- step
    def _decay_term(self, p, pv):
        """Coupled weight-decay gradient term for parameter ``p`` at value
        ``pv`` (the TRACED value under jit — reading p.value there would
        bake a stale constant).  None when no decay applies.  Decoupled
        optimizers (AdamW) override this to None and decay in _update."""
        wd = self._param_wd.get(id(p), self._weight_decay) \
            if p is not None else self._weight_decay
        if wd is None:
            return None
        from ..regularizer import L1Decay, L2Decay
        reg = p.regularizer if (p is not None and
                                getattr(p, "regularizer", None) is not None) \
            else wd
        if isinstance(reg, (int, float)):
            reg = L2Decay(float(reg))
        if isinstance(reg, (L1Decay, L2Decay)):
            return reg.grad_term(pv)
        return None

    def _apply_decay(self, p, g):
        term = self._decay_term(p, p.value)
        return g if term is None else g + term

    def _update_with_param(self, p, pv, g, state, lr, t):
        """Update rule with the Parameter in hand — the single funnel for
        BOTH the eager step and the compiled pytree path, so per-param
        behavior (AdamW/Lamb decay exclusion) can't diverge between
        them.  ``p`` may be None (pytree path without metadata)."""
        return self._update(pv, g, state, lr, t)

    def step(self):
        params_grads = []
        for p in self._parameters:
            if p is None or p.stop_gradient or p._grad is None:
                continue
            params_grads.append((p, p._grad))
        with _timeline.span("optimizer_step"):
            self._apply_gradients(params_grads)

    # ------------------------------------------------------- fused step
    def _fused_enabled(self):
        if not getattr(self, "_fused_supported", True):
            return False
        return os.environ.get("PADDLE_TPU_FUSED_STEP", "1") != "0"

    def _fused_hyper_token(self):
        """Hashable snapshot of every hyperparameter the compiled step
        bakes in.  Scalars by value; callables/objects (grad clip,
        schedulers, decay-exclusion fns) by identity — the cache key pins
        them alive, so id reuse cannot alias.  A live-updated Tensor beta
        (warmup schedules) changes the value snapshot and correctly forces
        a retrace."""
        toks = []
        for k in sorted(self.__dict__):
            if k in ("_step_count", "_lr", "_parameters", "_accumulators",
                     "_param_groups", "_param_wd", "_fused_cache",
                     "_fused_mutating"):
                continue
            v = self.__dict__[k]
            if v is None or isinstance(v, (bool, int, float, str)):
                toks.append((k, v))
            elif callable(v) or isinstance(v, (tuple, frozenset)):
                toks.append((k, _meta_token(v)))
        return tuple(toks)

    def _fused_signature(self, params, grads, states):
        per = []
        for p, g, st in zip(params, grads, states):
            if isinstance(p, Parameter):
                lr_mult = p.optimize_attr.get("learning_rate", 1.0)
                reg = _meta_token(p.regularizer)
                need_clip = bool(getattr(p, "need_clip", True))
            else:
                lr_mult, reg, need_clip = 1.0, None, True
            per.append((
                id(p), tuple(p.value.shape), str(p.value.dtype),
                tuple(g.shape), str(g.dtype), lr_mult, reg, need_clip,
                _meta_token(self._param_wd.get(id(p))),
                tuple(sorted((nm, str(a.dtype), tuple(a.shape))
                             for nm, a in st.items())),
            ))
        return (type(self), self._fused_hyper_token(),
                _meta_token(self._weight_decay),
                getattr(self, "_accumulator_placement", None) is not None,
                tuple(per))

    def _fused_lookup(self, key, build):
        """Signature-keyed compiled-step cache (a compile_cache site);
        ``build`` makes the jitted callable on a miss.  Unhashable key
        components surface as :class:`_UnhashableSignature` so the
        caller can retry next step."""
        try:
            compiled = self._fused_cache.lookup(key)
        except TypeError as e:
            raise _UnhashableSignature(str(e)) from e
        if compiled is None:
            compiled = build()
            self._fused_cache.insert(key, compiled)  # counts the compile
        return compiled

    def _commit_fused(self, params, new_ps, new_ss, t):
        """Adopt a compiled step's outputs.  Mutations only happen after
        the compiled call succeeded: a trace failure leaves the optimizer
        untouched for the eager fallback.  Conversely, once mutation
        starts, a failure must PROPAGATE (flagged via _fused_mutating) —
        falling back to the eager loop here would re-apply the same grads
        on top of half-updated state, a silent double step."""
        self._fused_mutating = True
        self._step_count = t
        _fused_stats["calls"] += 1
        place = getattr(self, "_accumulator_placement", None)
        pplace = getattr(self, "_param_placement", None)
        for p, nv, ns in zip(params, new_ps, new_ss):
            if pplace is not None:
                # ZeRO: pin updated params to their declared placement
                # (replicated for stage 1/2).  Without this, the jitted
                # step's inferred output shardings leak dp-sharded
                # params into the next eager forward, whose partitioned
                # matmuls then drift from the replicated run's numerics.
                nv = pplace(p, nv)
            p.value = nv
            for nm, sv in ns.items():
                if place is not None:
                    # ZeRO: keep moments dp-sharded across steps,
                    # exactly like the eager loop does
                    sv = place(p, sv)
                self._accumulators[nm][id(p)] = sv
        self._fused_mutating = False

    def _apply_gradients_fused(self, params_grads):
        pairs = [(p, (g.value if isinstance(g, Tensor) else g))
                 for p, g in params_grads if g is not None]
        if not pairs:
            self._step_count += 1
            return
        params = [p for p, _ in pairs]
        grads = [g for _, g in pairs]
        states = [self._state_for(p) for p in params]
        lr = self.get_lr()
        t = self._step_count + 1

        def build():
            def fused(param_vals, gs, sts, lr_, t_):
                return self.apply_updates_pytree(param_vals, gs, sts, lr_,
                                                 t_, params=params)
            donate = (0, 2) if _donation_enabled() else ()
            return jax.jit(fused, donate_argnums=donate)

        compiled = self._fused_lookup(
            self._fused_signature(params, grads, states), build)
        new_ps, new_ss = compiled([p.value for p in params], grads, states,
                                  lr, t)
        self._commit_fused(params, new_ps, new_ss, t)

    # ------------------------------------------- fused bucketed step
    def step_from_buckets(self, flats, layout, scale=1.0):
        """Consume a reducer's flat reduced buckets in ONE jitted
        scale+unflatten+update — no per-param unbucketing round-trip.

        ``flats``: list of flat reduced bucket arrays (SUM over ranks);
        ``layout``: [(param, flat_index, offset, numel, shape), ...];
        ``scale``: applied to every sliced grad inside the compiled step
        (1/nranks turns the reduced sum into the mean).  Params owned by
        this optimizer but absent from the layout (stop_gradient toggles,
        subset-group non-member buckets) ride the same compiled call with
        their direct ``.grad``.  Any failure before state mutation falls
        back to eager unbucketing + the normal step."""
        with _timeline.span("optimizer_step", fused_buckets=True):
            return self._step_from_buckets_impl(flats, layout, scale)

    def _step_from_buckets_impl(self, flats, layout, scale):
        in_layout = {id(p) for p, *_ in layout}
        extras = [(p, p._grad) for p in self._parameters
                  if p is not None and not p.stop_gradient
                  and p._grad is not None and id(p) not in in_layout]
        pairs = [(p, fi, off, n, shape) for p, fi, off, n, shape in layout
                 if not p.stop_gradient]
        if not self._fused_enabled():
            return self._apply_gradients(
                self._unbucket(flats, pairs, scale) + extras)
        try:
            return self._step_from_buckets_fused(flats, pairs, extras,
                                                 scale)
        except _UnhashableSignature:
            # possibly transient metadata — retry fused next step
            pass
        except Exception:                                  # noqa: BLE001
            if getattr(self, "_fused_mutating", False):
                self._fused_mutating = False
                raise
            # untraceable update rule: permanently fall back for this
            # instance, same as _apply_gradients — re-attempting the
            # failing trace every step would pay it forever
            self._fused_supported = False
        _fused_stats["eager_steps"] += 1
        return self._apply_gradients_eager(
            self._unbucket(flats, pairs, scale) + extras)

    @staticmethod
    def _unbucket(flats, pairs, scale):
        # raw jax arrays, exactly what step() feeds the eager loop — a
        # Tensor-wrapped grad would propagate Tensor into p.value
        return [(p, flats[fi][off:off + n].reshape(shape)
                 * jnp.asarray(scale, flats[fi].dtype))
                for p, fi, off, n, shape in pairs]

    def _step_from_buckets_fused(self, flats, pairs, extras, scale):
        params = [p for p, *_ in pairs] + [p for p, _ in extras]
        extra_grads = [(g.value if isinstance(g, Tensor) else g)
                       for _, g in extras]
        if not params:
            self._step_count += 1
            return
        states = [self._state_for(p) for p in params]
        lr = self.get_lr()
        t = self._step_count + 1
        slots = tuple((fi, int(off), int(n), tuple(shape))
                      for _, fi, off, n, shape in pairs)
        key = ("buckets", slots, float(scale),
               tuple((tuple(f.shape), str(f.dtype)) for f in flats),
               self._fused_signature(
                   params,
                   [jax.ShapeDtypeStruct(tuple(p.value.shape),
                                         p.value.dtype) for p in params],
                   states))

        def build():
            def fused(param_vals, flat_vals, extra_gs, sts, lr_, t_):
                grads = [flat_vals[fi][off:off + n].reshape(shape)
                         .astype(param_vals[i].dtype)
                         * jnp.asarray(scale, param_vals[i].dtype)
                         for i, (fi, off, n, shape) in enumerate(slots)]
                grads += list(extra_gs)
                return self.apply_updates_pytree(param_vals, grads, sts,
                                                 lr_, t_, params=params)
            donate = (0, 3) if _donation_enabled() else ()
            return jax.jit(fused, donate_argnums=donate)

        compiled = self._fused_lookup(key, build)
        new_ps, new_ss = compiled([p.value for p in params], list(flats),
                                  extra_grads, states, lr, t)
        self._commit_fused(params, new_ps, new_ss, t)

    def _apply_gradients(self, params_grads):
        if self._fused_enabled():
            try:
                return self._apply_gradients_fused(params_grads)
            except _UnhashableSignature:
                # possibly transient metadata — retry next step.  NOT a
                # bare TypeError: jax's ConcretizationTypeError and
                # TracerBoolConversionError subclass TypeError, and those
                # must reach the permanent-fallback branch below
                pass
            except Exception:                              # noqa: BLE001
                if getattr(self, "_fused_mutating", False):
                    # state already mutated: never re-apply (double step)
                    self._fused_mutating = False
                    raise
                # untraceable update rule (host sync, value-dependent
                # control flow): permanently fall back for this instance
                self._fused_supported = False
            _fused_stats["eager_steps"] += 1
            return self._apply_gradients_eager(params_grads)
        _fused_stats["eager_steps"] += 1
        return self._apply_gradients_eager(params_grads)

    def _apply_gradients_eager(self, params_grads):
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr_global = self.get_lr()
        self._step_count += 1
        pplace = getattr(self, "_param_placement", None)
        place = getattr(self, "_accumulator_placement", None)
        for p, g in params_grads:
            if g is None:
                continue
            g = self._apply_decay(p, g)
            lr = lr_global * p.optimize_attr.get("learning_rate", 1.0) \
                if isinstance(p, Parameter) else lr_global
            state = self._state_for(p)
            new_val, new_state = self._update_with_param(
                p, p.value, g, state, lr, self._step_count)
            if pplace is not None:
                # ZeRO: same placement pin as the fused commit path
                new_val = pplace(p, new_val)
            p.value = new_val
            for nm, sv in new_state.items():
                if place is not None:
                    # ZeRO: keep moments dp-sharded across eager updates
                    # (computation follows the unsharded grad otherwise)
                    sv = place(p, sv)
                self._accumulators[nm][id(p)] = sv

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static import graph as static_graph
        if static_graph.in_static_mode():
            # static build: register the train spec on the default program;
            # Executor.run differentiates the replayed graph with jax.grad
            prog = static_graph.default_main_program()
            loss_id = static_graph._ensure_var_id(loss, prog)
            prog.train_spec = (loss_id, self)
            if not self._parameters:
                self._parameters = prog.all_parameters()
            return None, None
        # classic recipe: loss.backward() THEN minimize(loss) — the
        # reference dygraph minimize HARVESTS existing grads and never
        # re-runs backward.  The tape stamps _backward_ran on the root:
        # testing that (not vjp_fn liveness — retain_graph=True keeps the
        # closures alive after a backward) prevents double-running; grad
        # presence would let a stale uncleared step suppress this one's
        node = getattr(loss, "_node", None)
        if (node is not None and node.vjp_fn is not None
                and not getattr(loss, "_backward_ran", False)):
            loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def clear_grad(self, set_to_zero=True):
        for p in self._parameters:
            if p is not None:
                p.clear_grad()

    clear_gradients = clear_grad

    # ----------------------------------------------------------- serialization
    def state_dict(self):
        sd = {}
        name_of = {}
        for p in self._parameters:
            name_of[id(p)] = p.name
        for nm, d in self._accumulators.items():
            for pid, arr in d.items():
                pname = name_of.get(pid, str(pid))
                # snapshot, don't alias: the fused step DONATES moment
                # buffers (on TPU), so a live-array reference taken here
                # would be deleted by the next step() before a save
                sd[f"{pname}_{nm}"] = Tensor(jnp.array(arr))
        sd["@step"] = self._step_count
        sd["@param_names"] = [p.name for p in self._parameters]
        if isinstance(self._lr, LRScheduler):
            sd["LR_Scheduler"] = self._lr.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("@step", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(dict(state_dict["LR_Scheduler"]))
        # auto-generated param names depend on layer-creation order, so a
        # resumed process's fresh layers may carry different names; map the
        # saved names onto the current parameters by position
        saved_names = state_dict.get("@param_names")
        for i, p in enumerate(self._parameters):
            # saved positional name first: the current auto-generated name
            # can collide with a DIFFERENT saved param's key when creation
            # order shifted between runs
            lookup_names = []
            if saved_names is not None and i < len(saved_names):
                lookup_names.append(saved_names[i])
            lookup_names.append(p.name)
            for nm in self._accum_names:
                for lname in lookup_names:
                    key = f"{lname}_{nm}"
                    if key in state_dict:
                        v = state_dict[key]
                        self._accumulators[nm][id(p)] = (
                            v.value if isinstance(v, Tensor)
                            else jnp.asarray(v))
                        break

    set_dict = set_state_dict

    # --------------------------------------------------- functional interface
    def init_state_pytree(self, params):
        """Pure-state init for the jitted train-step path: returns a pytree of
        accumulator dicts matching ``params`` (list of Tensors)."""
        return [
            {nm: self._init_accumulator(nm, p) for nm in self._accum_names}
            for p in params
        ]

    def apply_updates_pytree(self, param_vals, grads, states, lr, step=1,
                             params=None):
        """Pure function: apply the FULL update semantics — grad clip,
        weight decay/regularizers, per-param lr multipliers — across
        lists of arrays, exactly like the eager step (the compiled and
        eager paths must train identically).  Used inside jax.jit train
        steps (see hapi/model.py, static/graph.py).  ``params`` carries
        the Parameter objects aligned with param_vals; without it the
        per-param attrs are skipped (no fallback to self._parameters —
        its ordering is registration order, not the caller's)."""
        if self._grad_clip is not None:
            # clip classes are pure jnp over (param, raw-grad) pairs —
            # exactly what the eager step feeds them
            ps = params if params is not None else param_vals
            pairs = self._grad_clip(list(zip(ps, grads)))
            grads = [g for _, g in pairs]
        new_ps, new_ss = [], []
        for i, (pv, g, st) in enumerate(zip(param_vals, grads, states)):
            p = params[i] if params is not None else None
            term = self._decay_term(p, pv)
            if term is not None:
                g = g + term
            lr_i = lr
            if isinstance(p, Parameter):
                mult = p.optimize_attr.get("learning_rate", 1.0)
                if mult != 1.0:
                    lr_i = lr * mult
            np_, ns_ = self._update_with_param(p, pv, g, st, lr_i, step)
            new_ps.append(np_)
            new_ss.append(ns_)
        return new_ps, new_ss
