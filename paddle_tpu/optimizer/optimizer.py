"""Optimizer base (ref: python/paddle/optimizer/optimizer.py).

Each concrete optimizer defines a PURE update rule
``_update(p, g, state, lr) -> (new_p, new_state)`` over jax arrays.  Eager
``step()`` applies it per-parameter; the jitted train-step path (hapi/jit)
reuses the same rule inside one compiled function so the whole update fuses
into the step's HLO — the reference instead launches one CUDA kernel per op.
"""
from __future__ import annotations

import collections

import numpy as np
import jax.numpy as jnp

from ..framework import core
from ..tensor.tensor import Tensor, Parameter
from .lr import LRScheduler


class Optimizer:
    _accum_names: tuple = ()

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None else []
        self._grad_clip = grad_clip
        self._weight_decay = weight_decay
        self._accumulators = collections.defaultdict(dict)  # name -> {pid: arr}
        self._step_count = 0
        self._param_groups = None
        self._param_wd = {}       # id(p) -> per-group weight_decay override
        if (self._parameters and isinstance(self._parameters[0], dict)):
            self._param_groups = self._parameters
            self._parameters = []
            for g in self._param_groups:
                ps = list(g["params"])
                self._parameters.extend(ps)
                # per-group options ride the per-param mechanisms: the
                # group lr is a multiplier on the base lr (the ParamAttr
                # convention, ref optimizer.py:449 optimize_attr) and
                # weight_decay overrides the global one for these params
                for p in ps:
                    if "learning_rate" in g and isinstance(p, Parameter):
                        p.optimize_attr["learning_rate"] = float(
                            g["learning_rate"])
                    if "weight_decay" in g:
                        self._param_wd[id(p)] = g["weight_decay"]

    # ------------------------------------------------------------------ lr
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the lr is an LRScheduler instance")
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # ---------------------------------------------------------------- state
    def _state_for(self, p):
        key = id(p)
        states = {}
        for nm in self._accum_names:
            if key not in self._accumulators[nm]:
                self._accumulators[nm][key] = self._init_accumulator(nm, p)
            states[nm] = self._accumulators[nm][key]
        return states

    def _init_accumulator(self, name, p):
        zeros = jnp.zeros_like(p.value)
        place = getattr(self, "_accumulator_placement", None)
        if place is not None:      # ZeRO: dp-sharded moment placement
            zeros = place(p, zeros)
        return zeros

    def _update(self, p, g, state, lr, t=1):
        """Pure update rule.  ``t`` is the 1-based step count (python int
        eagerly, traced scalar under jit so bias correction doesn't force
        retraces)."""
        raise NotImplementedError

    # ---------------------------------------------------------------- step
    def _decay_term(self, p, pv):
        """Coupled weight-decay gradient term for parameter ``p`` at value
        ``pv`` (the TRACED value under jit — reading p.value there would
        bake a stale constant).  None when no decay applies.  Decoupled
        optimizers (AdamW) override this to None and decay in _update."""
        wd = self._param_wd.get(id(p), self._weight_decay) \
            if p is not None else self._weight_decay
        if wd is None:
            return None
        from ..regularizer import L1Decay, L2Decay
        reg = p.regularizer if (p is not None and
                                getattr(p, "regularizer", None) is not None) \
            else wd
        if isinstance(reg, (int, float)):
            reg = L2Decay(float(reg))
        if isinstance(reg, (L1Decay, L2Decay)):
            return reg.grad_term(pv)
        return None

    def _apply_decay(self, p, g):
        term = self._decay_term(p, p.value)
        return g if term is None else g + term

    def _update_with_param(self, p, pv, g, state, lr, t):
        """Update rule with the Parameter in hand — the single funnel for
        BOTH the eager step and the compiled pytree path, so per-param
        behavior (AdamW/Lamb decay exclusion) can't diverge between
        them.  ``p`` may be None (pytree path without metadata)."""
        return self._update(pv, g, state, lr, t)

    def step(self):
        params_grads = []
        for p in self._parameters:
            if p is None or p.stop_gradient or p._grad is None:
                continue
            params_grads.append((p, p._grad))
        self._apply_gradients(params_grads)

    def _apply_gradients(self, params_grads):
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr_global = self.get_lr()
        self._step_count += 1
        for p, g in params_grads:
            if g is None:
                continue
            g = self._apply_decay(p, g)
            lr = lr_global * p.optimize_attr.get("learning_rate", 1.0) \
                if isinstance(p, Parameter) else lr_global
            state = self._state_for(p)
            new_val, new_state = self._update_with_param(
                p, p.value, g, state, lr, self._step_count)
            p.value = new_val
            place = getattr(self, "_accumulator_placement", None)
            for nm, sv in new_state.items():
                if place is not None:
                    # ZeRO: keep moments dp-sharded across eager updates
                    # (computation follows the unsharded grad otherwise)
                    sv = place(p, sv)
                self._accumulators[nm][id(p)] = sv

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static import graph as static_graph
        if static_graph.in_static_mode():
            # static build: register the train spec on the default program;
            # Executor.run differentiates the replayed graph with jax.grad
            prog = static_graph.default_main_program()
            loss_id = static_graph._ensure_var_id(loss, prog)
            prog.train_spec = (loss_id, self)
            if not self._parameters:
                self._parameters = prog.all_parameters()
            return None, None
        # classic recipe: loss.backward() THEN minimize(loss) — the
        # reference dygraph minimize HARVESTS existing grads and never
        # re-runs backward.  Detect a prior backward by the loss's graph
        # state (consumed graphs free their vjp closures); grad presence
        # would let a stale uncleared step suppress this one's backward
        node = getattr(loss, "_node", None)
        if node is not None and node.vjp_fn is not None:
            loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def clear_grad(self, set_to_zero=True):
        for p in self._parameters:
            if p is not None:
                p.clear_grad()

    clear_gradients = clear_grad

    # ----------------------------------------------------------- serialization
    def state_dict(self):
        sd = {}
        name_of = {}
        for p in self._parameters:
            name_of[id(p)] = p.name
        for nm, d in self._accumulators.items():
            for pid, arr in d.items():
                pname = name_of.get(pid, str(pid))
                sd[f"{pname}_{nm}"] = Tensor(arr)
        sd["@step"] = self._step_count
        sd["@param_names"] = [p.name for p in self._parameters]
        if isinstance(self._lr, LRScheduler):
            sd["LR_Scheduler"] = self._lr.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("@step", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(dict(state_dict["LR_Scheduler"]))
        # auto-generated param names depend on layer-creation order, so a
        # resumed process's fresh layers may carry different names; map the
        # saved names onto the current parameters by position
        saved_names = state_dict.get("@param_names")
        for i, p in enumerate(self._parameters):
            # saved positional name first: the current auto-generated name
            # can collide with a DIFFERENT saved param's key when creation
            # order shifted between runs
            lookup_names = []
            if saved_names is not None and i < len(saved_names):
                lookup_names.append(saved_names[i])
            lookup_names.append(p.name)
            for nm in self._accum_names:
                for lname in lookup_names:
                    key = f"{lname}_{nm}"
                    if key in state_dict:
                        v = state_dict[key]
                        self._accumulators[nm][id(p)] = (
                            v.value if isinstance(v, Tensor)
                            else jnp.asarray(v))
                        break

    set_dict = set_state_dict

    # --------------------------------------------------- functional interface
    def init_state_pytree(self, params):
        """Pure-state init for the jitted train-step path: returns a pytree of
        accumulator dicts matching ``params`` (list of Tensors)."""
        return [
            {nm: self._init_accumulator(nm, p) for nm in self._accum_names}
            for p in params
        ]

    def apply_updates_pytree(self, param_vals, grads, states, lr, step=1,
                             params=None):
        """Pure function: apply the FULL update semantics — grad clip,
        weight decay/regularizers, per-param lr multipliers — across
        lists of arrays, exactly like the eager step (the compiled and
        eager paths must train identically).  Used inside jax.jit train
        steps (see hapi/model.py, static/graph.py).  ``params`` carries
        the Parameter objects aligned with param_vals; without it the
        per-param attrs are skipped (no fallback to self._parameters —
        its ordering is registration order, not the caller's)."""
        if self._grad_clip is not None:
            # clip classes are pure jnp over (param, raw-grad) pairs —
            # exactly what the eager step feeds them
            ps = params if params is not None else param_vals
            pairs = self._grad_clip(list(zip(ps, grads)))
            grads = [g for _, g in pairs]
        new_ps, new_ss = [], []
        for i, (pv, g, st) in enumerate(zip(param_vals, grads, states)):
            p = params[i] if params is not None else None
            term = self._decay_term(p, pv)
            if term is not None:
                g = g + term
            lr_i = lr
            if isinstance(p, Parameter):
                mult = p.optimize_attr.get("learning_rate", 1.0)
                if mult != 1.0:
                    lr_i = lr * mult
            np_, ns_ = self._update_with_param(p, pv, g, st, lr_i, step)
            new_ps.append(np_)
            new_ss.append(ns_)
        return new_ps, new_ss
