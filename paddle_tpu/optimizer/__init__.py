"""paddle_tpu.optimizer (ref: python/paddle/optimizer/__init__.py)."""
from .optimizer import Optimizer
from .optimizers import (SGD, Momentum, Adagrad, Adadelta, Adam, AdamW,
                         Adamax, RMSProp, Lamb)
from .gradient_merge import GradientMergeOptimizer
from . import lr
