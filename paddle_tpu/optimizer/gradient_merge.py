"""Gradient merge (accumulation) meta-optimizer.

TPU-native form of the reference's gradient-merge meta-optimizer
(ref: python/paddle/distributed/fleet/meta_optimizers/
gradient_merge_optimizer.py — it rewrites the static program to gate the
optimizer block behind a step-mod counter).  Here it wraps any eager
optimizer: every ``step()`` folds the current grads into on-device
accumulators and zeroes them; each ``k_steps``-th call applies the inner
optimizer on the (averaged) accumulated grads.  All accumulation is device
arithmetic — no host sync per micro-step, and the whole fold (one add per
parameter) is ONE jitted, buffer-donated call per micro-step instead of a
per-parameter dispatch loop (same amortization as the fused optimizer
step).  The boundary rescale fuses the same way.
"""
from __future__ import annotations

import collections

import jax

from .optimizer import _donation_enabled

# signature -> jitted tree-add / tree-scale executables (tiny: keyed on
# the aval tuple of the accumulated grads)
def _tree_site():
    # lazy: gradient_merge imports before the metrics registry in some
    # paths; the site materializes on first fused accumulate
    global _tree_cache
    if _tree_cache is None:
        from ..framework import compile_cache as _cc
        _tree_cache = _cc.site("fused_step.tree_ops", maxsize=16)
    return _tree_cache


_tree_cache = None


def _tree_op(kind, avals_key):
    donate = (0,) if _donation_enabled() else ()

    def build():
        if kind == "add":
            def f(accs, gs):
                return [a + g for a, g in zip(accs, gs)]
        else:                       # "scale"
            def f(accs, s):
                return [a * s for a in accs]
        return jax.jit(f, donate_argnums=donate)

    from ..framework.compile_cache import make_key
    return _tree_site().get(make_key(kind, avals_key, donate=donate),
                            build)


def _avals_key(arrs):
    return tuple((tuple(a.shape), str(a.dtype)) for a in arrs)


class GradientMergeOptimizer:
    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self._inner = inner_optimizer
        self._k = int(k_steps)
        self._avg = bool(avg)
        self._acc = {}          # id(param) -> accumulated grad value
        self._micro = 0

    @property
    def _parameters(self):
        return self._inner._parameters

    def step(self):
        self._micro += 1
        boundary = (self._micro % self._k) == 0
        fresh, fold = [], []
        for p in self._inner._parameters:
            if p is None or p._grad is None:
                continue
            g = p._grad         # raw device value (Tensor._grad slot)
            acc = self._acc.get(id(p))
            if acc is None:
                fresh.append((p, g))
            else:
                fold.append((p, acc, g))
            p._grad = None      # micro-step grads never reach the inner opt
        for p, g in fresh:
            self._acc[id(p)] = g
        if fold:
            # one fused, accumulator-donated add for the whole tree
            accs = [a for _, a, _ in fold]
            gs = [g for _, _, g in fold]
            out = _tree_op("add",
                           _avals_key(accs) + _avals_key(gs))(accs, gs)
            for (p, _, _), a in zip(fold, out):
                self._acc[id(p)] = a
        if not boundary:
            return
        scale = 1.0 / self._k if self._avg else 1.0
        # dedupe by id: a shared/tied Parameter listed twice must harvest
        # its accumulator once, not KeyError on the second pop
        with_acc, seen = [], set()
        for p in self._inner._parameters:
            if p is not None and id(p) in self._acc \
                    and id(p) not in seen:
                with_acc.append(p)
                seen.add(id(p))
        accs = [self._acc.pop(id(p)) for p in with_acc]
        if accs and scale != 1.0:
            accs = _tree_op("scale", _avals_key(accs))(accs, scale)
        for p, a in zip(with_acc, accs):
            p._grad = a
        self._inner.step()
        for p in self._inner._parameters:
            p._grad = None

    def clear_grad(self, set_to_zero=False):
        for p in self._inner._parameters:
            if p is not None:
                p._grad = None

    def minimize(self, loss, **kwargs):
        from ..static.graph import in_static_mode
        if in_static_mode():
            # static programs register train_spec through the inner
            # optimizer — accumulation does NOT happen there; warn out
            # loud (the silent k_steps-ignored case changes effective
            # batch size and update frequency 1:1)
            if self._k > 1:
                import warnings
                warnings.warn(
                    "GradientMergeOptimizer on the static Executor path "
                    f"applies a FULL update every run (k_steps={self._k} "
                    "is not accumulated there); feed k_steps micro-"
                    "batches per logical step yourself or use the "
                    "dygraph/hapi accumulation paths",
                    UserWarning, stacklevel=2)
            return self._inner.minimize(loss, **kwargs)
        if not any(p is not None and p._grad is not None
                   for p in self._inner._parameters):
            loss.backward()
        self.step()

    # delegate the rest of the optimizer surface
    def __getattr__(self, name):
        if name == "_inner":         # pre-__init__ lookups must not recurse
            raise AttributeError(name)
        return getattr(self._inner, name)
