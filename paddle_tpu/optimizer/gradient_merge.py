"""Gradient merge (accumulation) meta-optimizer.

TPU-native form of the reference's gradient-merge meta-optimizer
(ref: python/paddle/distributed/fleet/meta_optimizers/
gradient_merge_optimizer.py — it rewrites the static program to gate the
optimizer block behind a step-mod counter).  Here it wraps any eager
optimizer: every ``step()`` folds the current grads into on-device
accumulators and zeroes them; each ``k_steps``-th call applies the inner
optimizer on the (averaged) accumulated grads.  All accumulation is device
arithmetic — no host sync per micro-step.
"""
from __future__ import annotations


class GradientMergeOptimizer:
    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self._inner = inner_optimizer
        self._k = int(k_steps)
        self._avg = bool(avg)
        self._acc = {}          # id(param) -> accumulated grad value
        self._micro = 0

    @property
    def _parameters(self):
        return self._inner._parameters

    def step(self):
        self._micro += 1
        boundary = (self._micro % self._k) == 0
        for p in self._inner._parameters:
            if p is None or p._grad is None:
                continue
            g = p._grad         # raw device value (Tensor._grad slot)
            acc = self._acc.get(id(p))
            self._acc[id(p)] = g if acc is None else acc + g
            p._grad = None      # micro-step grads never reach the inner opt
        if not boundary:
            return
        scale = 1.0 / self._k if self._avg else 1.0
        for p in self._inner._parameters:
            acc = self._acc.pop(id(p), None)
            if acc is not None:
                p._grad = acc * scale
        self._inner.step()
        for p in self._inner._parameters:
            p._grad = None

    def clear_grad(self, set_to_zero=False):
        for p in self._inner._parameters:
            if p is not None:
                p._grad = None

    def minimize(self, loss, **kwargs):
        from ..static.graph import in_static_mode
        if in_static_mode():
            # static programs register train_spec through the inner
            # optimizer — accumulation does NOT happen there; warn out
            # loud (the silent k_steps-ignored case changes effective
            # batch size and update frequency 1:1)
            if self._k > 1:
                import warnings
                warnings.warn(
                    "GradientMergeOptimizer on the static Executor path "
                    f"applies a FULL update every run (k_steps={self._k} "
                    "is not accumulated there); feed k_steps micro-"
                    "batches per logical step yourself or use the "
                    "dygraph/hapi accumulation paths",
                    UserWarning, stacklevel=2)
            return self._inner.minimize(loss, **kwargs)
        if not any(p is not None and p._grad is not None
                   for p in self._inner._parameters):
            loss.backward()
        self.step()

    # delegate the rest of the optimizer surface
    def __getattr__(self, name):
        if name == "_inner":         # pre-__init__ lookups must not recurse
            raise AttributeError(name)
        return getattr(self._inner, name)
