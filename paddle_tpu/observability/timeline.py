"""Step-timeline tracing: nested spans, per-step records, and the rolling
JSON-lines event log (Dapper-style host-side tracing for the training
step; the device truth still rides jax.profiler/xprof).

Three sinks, all optional and all cheap when off:

* **chrome trace** — every span mirrors into ``paddle_tpu.profiler``'s
  event buffer (when a profiler session is active), so the existing
  ``export_chrome_tracing`` shows nested forward / backward / allreduce /
  optimizer / checkpoint spans with real step boundaries.
* **JSON-lines event log** — with ``PADDLE_TELEMETRY_DIR`` set (or
  :func:`configure` called), spans, per-step records, compile events and
  scalars append to ``events_rank<R>.jsonl`` in that directory, rotated
  at ``PADDLE_TELEMETRY_MAX_MB`` (default 64).  This is the artifact
  ``tools/telemetry_report.py`` and the launcher's ``--telemetry`` merge
  read, and what the fault supervisor's exit summary points into.
* **metrics registry** — step wall times, compile counts/seconds and
  collective-wait seconds land in ``observability.metrics`` counters and
  histograms, so ``metrics.snapshot()`` carries p50/p95 step times.

:class:`StepTimer` is the weave point: the training loop wraps each step
in ``timer.step()``; framework layers (reducer, optimizer, dataloader,
checkpoint) open :func:`span`\\ s that attribute their time to the active
step's phase breakdown.  XLA compile count+seconds come from the
``framework/jax_compat.py`` compile hook (one event per retrace); live
device memory from ``jax.local_devices()[*].memory_stats()`` where the
backend reports it (TPU yes, CPU no).
"""
from __future__ import annotations

import json
import os
import threading
import time

from . import metrics

_ENV_DIR = "PADDLE_TELEMETRY_DIR"
_ENV_MAX_MB = "PADDLE_TELEMETRY_MAX_MB"
_ENV_INTERVAL = "PADDLE_TELEMETRY_INTERVAL"


_rank_override = [None]


def set_rank_override(rank):
    """Pin this process's event-log rank (file name + stamped ``rank``).
    The fleet router calls this with its utility rank (1000) so its
    events land in ``events_rank1000.jsonl`` instead of colliding with
    replica 0's file when both share a telemetry dir — two processes
    appending and rotating one JSONL is how lines get torn.  ``None``
    reverts to the env knob."""
    with _writer_lock:
        _rank_override[0] = rank
        if _writer["file"] is not None:
            _writer["file"].close()
        _writer.update(dir=None, path=None, file=None, bytes=0)


def _rank():
    if _rank_override[0] is not None:
        return int(_rank_override[0])
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return 0


# --------------------------------------------------------------------------
# JSON-lines event writer (rolling)
# --------------------------------------------------------------------------

_writer_lock = threading.Lock()
_configured_dir = [None]        # programmatic override of the env knob
_writer = {"dir": None, "path": None, "file": None, "bytes": 0}


def telemetry_dir():
    """The active telemetry directory (``configure()`` override first,
    then ``PADDLE_TELEMETRY_DIR``), or None when telemetry is off."""
    return _configured_dir[0] or os.environ.get(_ENV_DIR) or None


def configure(directory):
    """Point the event log at ``directory`` (None reverts to the env
    knob).  Closes any open log file so the next emit reopens there."""
    with _writer_lock:
        _configured_dir[0] = directory
        if _writer["file"] is not None:
            _writer["file"].close()
        _writer.update(dir=None, path=None, file=None, bytes=0)


def _max_bytes():
    try:
        return int(float(os.environ.get(_ENV_MAX_MB, "64")) * (1 << 20))
    except ValueError:
        return 64 << 20


def _open_writer(d):
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"events_rank{_rank()}.jsonl")
    f = open(path, "a", encoding="utf-8")
    _writer.update(dir=d, path=path, file=f,
                   bytes=os.path.getsize(path))


def emit(record):
    """Append one structured event to the rolling JSONL log (no-op when
    telemetry is off).  ``time`` and ``rank`` are stamped here."""
    d = telemetry_dir()
    if not d:
        return False
    rec = {"time": round(time.time(), 6), "rank": _rank()}
    rec.update(record)
    line = json.dumps(rec, sort_keys=True) + "\n"
    with _writer_lock:
        if _writer["dir"] != d or _writer["file"] is None:
            if _writer["file"] is not None:
                _writer["file"].close()
            _open_writer(d)
        f = _writer["file"]
        f.write(line)
        f.flush()
        _writer["bytes"] += len(line)
        if _writer["bytes"] > _max_bytes():
            # roll: current log becomes .1 (one generation kept), fresh file
            f.close()
            os.replace(_writer["path"], _writer["path"] + ".1")
            _open_writer(d)
    return True


# --------------------------------------------------------------------------
# spans
# --------------------------------------------------------------------------

_tls = threading.local()


def _span_stack():
    st = getattr(_tls, "spans", None)
    if st is None:
        st = _tls.spans = []
    return st


def _profiler_mod():
    import sys
    return sys.modules.get("paddle_tpu.profiler")


def active():
    """True when any span sink wants data: a profiler session is on, a
    telemetry dir is configured, or a StepTimer is live.  Framework
    instrumentation points gate on this so the off path costs one
    attribute read."""
    if _active_timers:
        return True
    prof = _profiler_mod()
    if prof is not None and prof.is_enabled():
        return True
    return telemetry_dir() is not None


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL = _NullCtx()


class _Span:
    __slots__ = ("name", "attrs", "_t0")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        _span_stack().append(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *a):
        dur = time.perf_counter() - self._t0
        st = _span_stack()
        depth = len(st) - 1
        if st and st[-1] == self.name:
            st.pop()
        prof = _profiler_mod()
        if prof is not None and prof.is_enabled():
            prof.record_op(self.name, dur, t_start=self._t0)
        ctx = current_step()
        if ctx is not None:
            ctx._add_phase(self.name, dur)
        if telemetry_dir():
            rec = {"event": "span", "name": self.name, "depth": depth,
                   "dur_s": round(dur, 6)}
            if self.attrs:
                rec.update(self.attrs)
            emit(rec)
        return False


def span(name, **attrs):
    """Nested timing span.  Returns a shared no-op context when no sink
    is active — safe to leave in hot paths."""
    if not active():
        return _NULL
    return _Span(name, attrs)


# --------------------------------------------------------------------------
# compile hook + collective wait (feed both the registry and step records)
# --------------------------------------------------------------------------

_compile_hook_done = [False]


def install_compile_hook():
    """Route XLA compile events (one per retrace, via the jax.monitoring
    listener in framework/jax_compat.py) into the registry, the chrome
    trace and the event log.  Idempotent."""
    if _compile_hook_done[0]:
        return False
    _compile_hook_done[0] = True
    from ..framework import jax_compat
    return jax_compat.install_compile_hook(_on_compile)


def _on_compile(kind, seconds):
    metrics.counter("compile.count").inc()
    metrics.counter("compile.seconds").inc(seconds)
    metrics.histogram("compile.duration_s").observe(seconds)
    prof = _profiler_mod()
    if prof is not None and prof.is_enabled():
        prof.record_op("xla_compile",
                       seconds, t_start=time.perf_counter() - seconds)
    if telemetry_dir():
        emit({"event": "compile", "kind": kind,
              "dur_s": round(seconds, 6)})


def record_collective_wait(seconds, op=None):
    """Called by the eager cross-process collective transport with the
    time this rank spent blocked at the rendezvous (NOT the time it
    spent producing its contribution).  A straggler therefore shows the
    LOWEST wait — everyone else was waiting on it — which is exactly
    what the cross-rank merge's straggler detector keys on."""
    metrics.counter("collective.wait_s").inc(seconds)
    metrics.counter("collective.waits").inc()
    metrics.histogram("collective.wait_duration_s",
                      op=op or "unknown").observe(seconds)


def device_memory():
    """Per-device live memory, where the backend reports it
    ({device: {bytes_in_use, peak_bytes_in_use, ...}}); None on backends
    without memory_stats (CPU)."""
    try:
        import jax
        out = {}
        for d in jax.local_devices():
            st = d.memory_stats()
            if st:
                out[str(d.id)] = {
                    k: st[k] for k in ("bytes_in_use", "peak_bytes_in_use",
                                       "bytes_limit") if k in st}
        return out or None
    except Exception:                                      # noqa: BLE001
        return None


# --------------------------------------------------------------------------
# StepTimer
# --------------------------------------------------------------------------

_active_timers = []          # innermost-last; step() attaches to [-1]


def current_timer():
    return _active_timers[-1] if _active_timers else None


def current_step():
    t = current_timer()
    return t._current if t is not None else None


class _StepCtx:
    def __init__(self, timer, tokens):
        self.timer = timer
        self.tokens = tokens
        self.phases = {}
        self._lock = threading.Lock()

    def _add_phase(self, name, dur):
        with self._lock:
            self.phases[name] = self.phases.get(name, 0.0) + dur

    def __enter__(self):
        self.timer._current = self
        self._t0 = time.perf_counter()
        self._compiles0 = metrics.counter("compile.count").value
        self._compile_s0 = metrics.counter("compile.seconds").value
        self._wait0 = metrics.counter("collective.wait_s").value
        return self

    def __exit__(self, exc_type, *a):
        dur = time.perf_counter() - self._t0
        timer = self.timer
        timer._current = None
        if exc_type is not None:
            return False
        timer._step_idx += 1
        timer.step_times.append(dur)
        timer._hist.observe(dur)
        metrics.counter("step.count").inc()
        metrics.gauge("step.last_wall_s").set(round(dur, 6))
        tokens = self.tokens if self.tokens is not None \
            else timer.tokens_per_step
        tps = (tokens / dur) if tokens and dur > 0 else None
        if tps is not None:
            metrics.gauge("step.tokens_per_s").set(round(tps, 3))
        prof = _profiler_mod()
        if prof is not None and prof.is_enabled():
            prof.record_op("step", dur, t_start=self._t0)
        record = {
            "event": "step", "name": timer.name, "step": timer._step_idx,
            "wall_s": round(dur, 6),
            "tokens": tokens, "tokens_per_s":
                round(tps, 3) if tps is not None else None,
            "compiles":
                metrics.counter("compile.count").value - self._compiles0,
            "compile_s": round(
                metrics.counter("compile.seconds").value
                - self._compile_s0, 6),
            "collective_wait_s": round(
                metrics.counter("collective.wait_s").value - self._wait0, 6),
            "phases": {k: round(v, 6) for k, v in self.phases.items()},
        }
        # device memory is only worth its per-step host query when the
        # record actually lands somewhere (the JSONL log) — a StepTimer
        # wrapped around a microbenchmark hot loop with telemetry off
        # must not pay jax.local_devices()+memory_stats() every step
        if telemetry_dir():
            mem = device_memory()
            if mem:
                record["device_mem"] = mem
        timer.last_record = record
        emit(record)
        timer._maybe_publish()
        return False


class StepTimer:
    """Per-step wall-clock timeline for a training loop.

    >>> with StepTimer(tokens_per_step=batch * seq) as timer:
    ...     for batch in loader:
    ...         with timer.step():
    ...             with timer.span("forward"):
    ...                 loss = net(x)
    ...             with timer.span("backward"):
    ...                 loss.backward()
    ...             opt.step()          # spans itself via the framework

    Each step emits one structured record (wall time, tokens/s, XLA
    compile count+seconds, collective wait, phase breakdown, device
    memory) into the JSONL event log, observes the ``step.wall_s``
    histogram (p50/p95 in ``metrics.snapshot()``), and — every
    ``PADDLE_TELEMETRY_INTERVAL`` seconds (default 10) in a
    multi-process run — publishes this rank's snapshot for the
    cross-rank aggregator."""

    def __init__(self, name="train", tokens_per_step=None,
                 publish_interval=None, start_step=0):
        self.name = name
        self.tokens_per_step = tokens_per_step
        self.step_times = []
        self.last_record = None
        # a resumed worker passes its restored step so records carry TRUE
        # training-step numbers (the offline merge dedupes replays on
        # them; an incarnation-local 1..k numbering would double-count)
        self._step_idx = int(start_step)
        self._current = None
        self._hist = metrics.histogram("step.wall_s")
        if publish_interval is None:
            try:
                publish_interval = float(
                    os.environ.get(_ENV_INTERVAL, "10"))
            except ValueError:
                publish_interval = 10.0
        self.publish_interval = publish_interval
        self._last_publish = time.monotonic()
        install_compile_hook()

    # ------------------------------------------------------------ session
    def __enter__(self):
        _active_timers.append(self)
        return self

    def __exit__(self, *a):
        if self in _active_timers:
            _active_timers.remove(self)
        return False

    def step(self, tokens=None):
        """Context manager timing ONE training step."""
        return _StepCtx(self, tokens)

    def span(self, name, **attrs):
        return span(name, **attrs)

    @property
    def steps(self):
        return self._step_idx

    # ------------------------------------------------------------- stats
    def percentiles(self):
        """{"mean","p50","p95"} seconds over this timer's own steps."""
        if not self.step_times:
            return {"mean": None, "p50": None, "p95": None}
        data = sorted(self.step_times)

        def pct(p):
            rank = max(int(-(-p / 100.0 * len(data) // 1)), 1)
            return data[min(rank, len(data)) - 1]

        return {"mean": sum(data) / len(data), "p50": pct(50),
                "p95": pct(95)}

    def throughput(self, window=20):
        """(steps/s, tokens/s or None) over the last ``window`` steps."""
        recent = self.step_times[-window:]
        if not recent:
            return 0.0, None
        dt = sum(recent)
        sps = len(recent) / dt if dt > 0 else 0.0
        tps = sps * self.tokens_per_step if self.tokens_per_step else None
        return sps, tps

    # ----------------------------------------------------------- publish
    def _maybe_publish(self):
        if self.publish_interval <= 0:
            return
        now = time.monotonic()
        if now - self._last_publish < self.publish_interval:
            return
        self._last_publish = now
        try:
            from . import aggregate
            aggregate.publish(step=self._step_idx)
        except Exception:                                  # noqa: BLE001
            pass                # telemetry must never kill a training loop
