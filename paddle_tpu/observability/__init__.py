"""Unified telemetry layer (ISSUE 4): the structured metrics registry,
the step-timeline tracer, and cross-rank aggregation.

* :mod:`.metrics` — thread-safe counters/gauges/histograms with labels;
  the single store behind every legacy ``*_stats()`` family
  (``metrics.snapshot()``, Prometheus text, JSONL export).
* :mod:`.timeline` — :class:`StepTimer` + nested spans feeding the
  chrome-trace exporter and the rolling JSONL event log
  (``PADDLE_TELEMETRY_DIR``), XLA compile events, device memory.
* :mod:`.aggregate` — per-rank snapshot publish through the KV store /
  telemetry dir, and the group-wide merge with straggler detection
  (``tools/telemetry_report.py`` renders it).
* :mod:`.tracing` — fleet-wide distributed request tracing (ISSUE 19):
  router-minted trace ids, per-hop span events riding the timeline
  JSONL, the in-memory incident flight recorder, and the coherent
  per-process clock; ``aggregate`` stitches the per-rank events into
  causally-ordered lifecycles (``tools/trace_report.py`` renders them).

Registered families include the training fast paths (``dispatch_cache``,
``fused_step``, ``reducer``, ``prefetch``, ``faults``) and the inference
side's ``serving.*`` (queue depth / slot occupancy gauges, prefill and
decode latency histograms, bucket/standalone compile counters) plus
``compile.persistent_cache_*`` from the ``PADDLE_JIT_CACHE_DIR``
persistent-compilation-cache hook.

``metrics`` is strictly stdlib so pre-jax modules (the launcher, the
fault registry, the bootstrap) can register families; ``timeline`` and
``aggregate`` import jax only lazily inside functions.
"""
from . import metrics        # noqa: F401
from . import timeline       # noqa: F401
from . import tracing        # noqa: F401
from . import aggregate      # noqa: F401
from .timeline import StepTimer, span  # noqa: F401
