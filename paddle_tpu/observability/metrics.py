"""Structured metrics registry — the single store behind every host-side
counter family in paddle_tpu (design after the Prometheus client-library
data model and MegaScale's per-step diagnostics).

PRs 1–3 each grew an ad-hoc module-level stat dict (``dispatch_cache``,
``fused_step``, ``reducer``, ``prefetch`` and the composite ``faults``
family), reachable only through ``profiler.fast_path_summary()``.  This
registry absorbs them: each module's stat dict is now a
:class:`StatsFamily` — a mutable-mapping VIEW whose storage IS the
registry's counters — so the old ``*_stats()`` functions, the bench
assertions and ``fast_path_summary()`` keep working unchanged while
``metrics.snapshot()`` / ``to_prometheus()`` / ``export_jsonl()`` see the
same numbers with no dual bookkeeping.

Metric types:

* :class:`Counter` — monotonic int/float, ``inc()`` under the registry
  lock (threaded increments lose nothing).
* :class:`Gauge` — last-write-wins scalar.
* :class:`Histogram` — count/sum/min/max + cumulative buckets for the
  Prometheus exposition, plus a bounded reservoir of raw observations so
  ``percentile(50)`` / ``percentile(95)`` report real quantiles (the
  bench step-time p50/p95), not bucket midpoints.

Labels: ``counter("name", rank="0")`` keys the metric on (name, sorted
label items); the same name with different labels is a distinct series,
exactly the Prometheus model.

Strictly stdlib: this module is imported by ``_dist_bootstrap``,
``testing/faults.py`` and the launcher — all of which must be importable
before jax initializes a backend.
"""
from __future__ import annotations

import json
import threading
import time
from collections.abc import MutableMapping

# default histogram bucket bounds (seconds-flavored, exponential): wide
# enough for step times and compile times alike
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_RESERVOIR_CAP = 4096


def nearest_rank_percentile(sorted_data, p):
    """Nearest-rank (ceil) percentile over an ALREADY-SORTED sequence;
    None with no data.  The one shared implementation of the idiom —
    histograms, the fleet's latency windows, and the bench all key
    their p50/p99 numbers on it."""
    if not sorted_data:
        return None
    rank = max(int(-(-p / 100.0 * len(sorted_data) // 1)), 1)  # ceil
    return sorted_data[min(rank, len(sorted_data)) - 1]


def _label_key(labels):
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _series_name(name, label_key):
    if not label_key:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in label_key)
    return f"{name}{{{inner}}}"


def _prom_name(name):
    out = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    return out if not out[:1].isdigit() else "_" + out


class _Metric:
    kind = "untyped"

    def __init__(self, registry, name, label_key):
        self._registry = registry
        self._lock = registry._lock
        self.name = name
        self.labels = dict(label_key)
        self._label_key = label_key

    @property
    def series(self):
        return _series_name(self.name, self._label_key)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, registry, name, label_key):
        super().__init__(registry, name, label_key)
        self._value = 0

    def inc(self, v=1):
        with self._lock:
            self._value += v

    def set(self, v):
        """Assignment exists for the legacy dict-view ``stats[k] = 0``
        reset idiom; new code should only ever ``inc()``."""
        with self._lock:
            self._value = v

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self):
        self.set(0)


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, registry, name, label_key):
        super().__init__(registry, name, label_key)
        self._value = 0.0

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, v=1):
        with self._lock:
            self._value += v

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self):
        self.set(0.0)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, registry, name, label_key, buckets=None):
        super().__init__(registry, name, label_key)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._counts = [0] * (len(self.buckets) + 1)   # +inf tail
        self._reservoir = []
        self._res_next = 0
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            i = 0
            for i, le in enumerate(self.buckets):
                if v <= le:
                    break
            else:
                i = len(self.buckets)
            self._counts[i] += 1
            # bounded ring: recent observations win (a rolling window is
            # what step-time percentiles should describe anyway)
            if len(self._reservoir) < _RESERVOIR_CAP:
                self._reservoir.append(v)
            else:
                self._reservoir[self._res_next] = v
                self._res_next = (self._res_next + 1) % _RESERVOIR_CAP

    def percentile(self, p):
        """Nearest-rank percentile over the (bounded) reservoir of raw
        observations; None with no data."""
        with self._lock:
            data = sorted(self._reservoir)
        return nearest_rank_percentile(data, p)

    @property
    def mean(self):
        with self._lock:
            return self.sum / self.count if self.count else None

    def cumulative_buckets(self):
        """[(le, cumulative_count), ...] ending with ('+Inf', count)."""
        with self._lock:
            out, acc = [], 0
            for le, c in zip(self.buckets, self._counts):
                acc += c
                out.append((le, acc))
            out.append(("+Inf", acc + self._counts[-1]))
            return out

    def summary(self):
        with self._lock:
            n = self.count
            s = {"count": n, "sum": round(self.sum, 9),
                 "min": self.min, "max": self.max,
                 "mean": (self.sum / n if n else None)}
        s["p50"] = self.percentile(50)
        s["p95"] = self.percentile(95)
        return s

    def reset(self):
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._reservoir = []
            self._res_next = 0
            self.count = 0
            self.sum = 0.0
            self.min = self.max = None


class StatsFamily(MutableMapping):
    """Dict-shaped VIEW over a group of registry counters (one family of
    related keys, e.g. ``reducer``).  The legacy module-level stat dicts
    are these: ``stats["hits"] += 1``, ``dict(stats)``, iteration and
    ``update()`` all behave like the plain dict they replaced, but the
    storage is the registry's counters — ``metrics.snapshot()`` and the
    old ``*_stats()`` views read the same cells."""

    def __init__(self, registry, family, defaults=None):
        self._registry = registry
        self.family = family
        self._counters = {}
        for k, v in (defaults or {}).items():
            c = registry.counter(f"{family}.{k}")
            if v:
                c.set(v)
            self._counters[k] = c

    def _counter(self, key):
        c = self._counters.get(key)
        if c is None:
            c = self._counters[key] = self._registry.counter(
                f"{self.family}.{key}")
        return c

    def __getitem__(self, key):
        return self._counters[key].value

    def __setitem__(self, key, value):
        self._counter(key).set(value)

    def __delitem__(self, key):
        del self._counters[key]

    def __iter__(self):
        return iter(self._counters)

    def __len__(self):
        return len(self._counters)

    def inc(self, key, v=1):
        """Atomic increment — preferred over ``stats[k] += 1`` (which is
        a read-then-write) for counters bumped from several threads."""
        self._counter(key).inc(v)

    def reset(self):
        for c in self._counters.values():
            c.reset()


class MetricsRegistry:
    """Thread-safe name->metric store.  One process-wide instance
    (``metrics.REGISTRY``) backs every paddle_tpu counter; private
    instances exist only for tests."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics = {}          # (name, label_key) -> metric
        self._families = {}         # family name -> StatsFamily

    # ------------------------------------------------------- constructors
    def _get_or_create(self, cls, name, labels, **kw):
        key = (name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = cls(self, name, key[1], **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {key[0]!r} already registered as {m.kind}")
            return m

    def counter(self, name, **labels):
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name, **labels):
        return self._get_or_create(Gauge, name, labels)

    def histogram(self, name, buckets=None, **labels):
        return self._get_or_create(Histogram, name, labels, buckets=buckets)

    def stats_family(self, family, defaults=None):
        """Get-or-create the dict-view for ``family``; re-registration
        merges any new default keys (module reloads in tests)."""
        with self._lock:
            fam = self._families.get(family)
            if fam is None:
                fam = self._families[family] = StatsFamily(
                    self, family, defaults)
            else:
                for k in (defaults or {}):
                    fam._counter(k)
            return fam

    # ------------------------------------------------------------- views
    def _sorted_metrics(self):
        with self._lock:
            return sorted(self._metrics.values(),
                          key=lambda m: (m.name, m._label_key))

    def snapshot(self):
        """Flat ``{series_name: value}`` — counters/gauges by value,
        histograms by their summary dict."""
        out = {}
        for m in self._sorted_metrics():
            out[m.series] = (m.summary() if isinstance(m, Histogram)
                             else m.value)
        return out

    def families(self):
        """``{family: {key: value}}`` for every registered StatsFamily —
        the exact numbers the legacy ``*_stats()`` views serve."""
        with self._lock:
            fams = list(self._families.values())
        return {f.family: dict(f) for f in fams}

    def reset(self, family=None):
        """Zero every metric (or only one family's counters).  The one
        sanctioned replacement for the per-family ``reset_*_stats()``
        helpers."""
        if family is not None:
            with self._lock:
                fam = self._families.get(family)
            if fam is not None:
                fam.reset()
            return
        for m in self._sorted_metrics():
            m.reset()

    # ------------------------------------------------------------ exports
    def to_prometheus(self):
        """Prometheus text exposition (v0.0.4) of every metric."""
        lines = []
        seen_types = set()
        for m in self._sorted_metrics():
            pname = _prom_name(m.name)
            if pname not in seen_types:
                seen_types.add(pname)
                lines.append(f"# TYPE {pname} {m.kind}")
            label_s = ("{" + ",".join(f'{_prom_name(k)}="{v}"'
                                      for k, v in m._label_key) + "}"
                       if m._label_key else "")
            if isinstance(m, Histogram):
                base = m._label_key
                for le, acc in m.cumulative_buckets():
                    le_s = le if le == "+Inf" else repr(float(le))
                    extra = base + (("le", le_s),)
                    inner = ",".join(f'{_prom_name(k)}="{v}"'
                                     for k, v in extra)
                    lines.append(f"{pname}_bucket{{{inner}}} {acc}")
                lines.append(f"{pname}_sum{label_s} {m.sum}")
                lines.append(f"{pname}_count{label_s} {m.count}")
            else:
                lines.append(f"{pname}{label_s} {m.value}")
        return "\n".join(lines) + "\n"

    def export_jsonl(self):
        """One JSON object per metric (machine-ingestable lines for the
        telemetry event log): name, labels, type, value/summary."""
        now = time.time()
        lines = []
        for m in self._sorted_metrics():
            rec = {"event": "metric", "time": round(now, 6),
                   "name": m.name, "type": m.kind, "labels": m.labels}
            if isinstance(m, Histogram):
                rec["summary"] = m.summary()
            else:
                rec["value"] = m.value
            lines.append(json.dumps(rec, sort_keys=True))
        return lines


# the process-wide registry every paddle_tpu family registers into
REGISTRY = MetricsRegistry()


def counter(name, **labels):
    return REGISTRY.counter(name, **labels)


def gauge(name, **labels):
    return REGISTRY.gauge(name, **labels)


def histogram(name, buckets=None, **labels):
    return REGISTRY.histogram(name, buckets=buckets, **labels)


def stats_family(family, defaults=None):
    return REGISTRY.stats_family(family, defaults)


def snapshot():
    return REGISTRY.snapshot()


def families():
    return REGISTRY.families()


def reset(family=None):
    REGISTRY.reset(family)


def to_prometheus():
    return REGISTRY.to_prometheus()


def export_jsonl():
    return REGISTRY.export_jsonl()
