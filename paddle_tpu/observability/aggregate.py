"""Cross-rank telemetry aggregation: ranks publish periodic metric
snapshots (through the jax.distributed coordination-service KV store
and/or per-rank files in the telemetry dir), and rank 0 — or any offline
reader (``tools/telemetry_report.py``, ``launch.py --telemetry``) —
merges them into a group-wide view: per-rank step progress and step-time
stats, step skew, straggler detection, and fault counters by rank.

Straggler detection keys on **collective wait asymmetry**, the MegaScale
diagnostic: every rank's rendezvous wait is recorded by the collective
transport (timeline.record_collective_wait), and a straggler is the rank
everyone else waits on — its own wait is the LOWEST while the group's is
high.  A rank whose wait-per-step undercuts the group maximum by more
than ``PADDLE_TELEMETRY_STRAGGLER`` seconds (default 0.2) is flagged, as
is any rank lagging the group's step frontier by more than
``PADDLE_TELEMETRY_STEP_LAG`` steps (default 2).
"""
from __future__ import annotations

import glob
import json
import os
import time
import warnings

from . import metrics, timeline

KV_PREFIX = "paddle_tpu_telemetry"

# ranks at/above this publish infrastructure counter snapshots (fleet
# router = 1000, lint CLI = 1001), not per-step training progress
UTILITY_RANK_BASE = 1000

_publish_seq = [0]
_last_kv_key = {}          # rank -> this incarnation's last published key


def _next_seq():
    """Monotonic across SUPERVISED RESTARTS, not just within this
    process: a relaunched worker's fresh counter must still outrank its
    pre-crash publishes (gather() keeps the highest seq per rank), so
    the sequence is wall-clock-derived with a strictly-increasing
    fallback for publishes landing in the same millisecond."""
    seq = max(int(time.time() * 1000), _publish_seq[0] + 1)
    _publish_seq[0] = seq
    return seq


def _default_straggler_gap():
    try:
        return float(os.environ.get("PADDLE_TELEMETRY_STRAGGLER", "0.2"))
    except ValueError:
        return 0.2


def _default_step_lag():
    try:
        return int(os.environ.get("PADDLE_TELEMETRY_STEP_LAG", "2"))
    except ValueError:
        return 2


def _kv_client():
    """The live coordination-service client, or None outside a
    multi-process launch."""
    try:
        import jax
        if jax.process_count() <= 1:
            return None
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:                                      # noqa: BLE001
        return None


# --------------------------------------------------------------------------
# per-rank snapshots
# --------------------------------------------------------------------------

def snapshot_record(step=None, rank=None):
    """This rank's publishable telemetry snapshot: registry families,
    step-time summary, compile + collective-wait totals."""
    hist = metrics.histogram("step.wall_s")
    return {
        "rank": _rank() if rank is None else int(rank),
        "time": round(time.time(), 6),
        "step": step,
        "steps": hist.count,
        "step_wall": hist.summary(),
        "compiles": metrics.counter("compile.count").value,
        "compile_s": round(metrics.counter("compile.seconds").value, 6),
        "collective_wait_s": round(
            metrics.counter("collective.wait_s").value, 6),
        "families": metrics.families(),
    }


def _rank():
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return 0


def publish(step=None, client=None, rank=None):
    """Publish this rank's snapshot: atomically to
    ``<telemetry_dir>/snapshot_rank<R>.json`` when a telemetry dir is
    active, and to the KV store under a fresh sequence key (the previous
    one is deleted best-effort so per-interval publishes don't grow the
    coordinator's store).  Returns the snapshot dict."""
    snap = snapshot_record(step=step, rank=rank)
    d = timeline.telemetry_dir()
    if d:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"snapshot_rank{snap['rank']}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f, sort_keys=True)
        os.replace(tmp, path)
    client = client if client is not None else _kv_client()
    if client is not None:
        key = f"{KV_PREFIX}/r{snap['rank']}/{_next_seq()}"
        try:
            client.key_value_set(key, json.dumps(snap, sort_keys=True))
            prev = _last_kv_key.get(snap["rank"])
            if prev is not None:
                try:                 # reclaim THIS incarnation's previous
                    client.key_value_delete(prev)   # key (bounded store);
                except Exception:                          # noqa: BLE001
                    pass             # a crashed incarnation leaves one
            _last_kv_key[snap["rank"]] = key        # stale, shadowed key
        except Exception:                                  # noqa: BLE001
            pass            # telemetry publish must never fail training
    return snap


def gather(client=None):
    """Every rank's LATEST published KV snapshot (highest sequence per
    rank), as a list sorted by rank.  [] when no client / nothing
    published."""
    client = client if client is not None else _kv_client()
    if client is None:
        return []
    try:
        entries = client.key_value_dir_get(KV_PREFIX)
    except Exception:                                      # noqa: BLE001
        return []
    latest = {}
    for key, value in entries:
        parts = key.split("/")
        try:
            rank = int(parts[-2].lstrip("r"))
            seq = int(parts[-1])
        except (IndexError, ValueError):
            continue
        if rank not in latest or seq > latest[rank][0]:
            latest[rank] = (seq, value)
    out = []
    for rank in sorted(latest):
        try:
            out.append(json.loads(latest[rank][1]))
        except ValueError:
            continue
    return out


# --------------------------------------------------------------------------
# merge
# --------------------------------------------------------------------------

def merge(snapshots, straggler_gap_s=None, step_lag=None, warn=False):
    """Merge per-rank snapshots into the group-wide report.

    Returns a dict with per-rank step progress and step-time stats
    (mean/p50/p95), the group step skew, flagged ``stragglers`` (each
    naming the rank and why), and per-rank fault counters.  With
    ``warn=True`` every straggler also raises a RuntimeWarning — the
    live rank-0 merge path."""
    if straggler_gap_s is None:
        straggler_gap_s = _default_straggler_gap()
    if step_lag is None:
        step_lag = _default_step_lag()
    ranks = {}
    for snap in snapshots:
        r = int(snap.get("rank", 0))
        wall = snap.get("step_wall") or {}
        steps = snap.get("steps") or 0
        faults = {}
        fams = snap.get("families") or {}
        # "fleet" rides along: the router's requeues/sheds/heartbeat
        # misses are fault counters in every sense that matters here —
        # and "autoscale" with it (scale decisions/errors are incidents
        # the group view should surface); from "analysis" (lint posture,
        # published by the CLI under rank 1001) only the findings_*
        # counters qualify — files_scanned/suppressed/baseline_size are
        # gauges a CLEAN run reports nonzero, not incidents
        # "journal" (ISSUE 18) likewise filtered: appends/syncs/
        # compactions are routine WAL traffic; only the DAMAGE counters
        # (torn tails, corrupt records) are incidents
        for fam in ("faults", "watchdog", "launch", "checkpoint",
                    "bootstrap", "fleet", "autoscale", "analysis",
                    "journal"):
            for k, v in (fams.get(fam) or {}).items():
                if fam == "analysis" and not k.startswith("findings_"):
                    continue
                if fam == "journal" and k not in ("corrupt_records",
                                                  "torn_tails"):
                    continue
                if v:
                    faults[f"{fam}.{k}"] = v
        # the KV host-tier family (ISSUE 17): spill/fault-back traffic
        # is routine operation, not an incident — its own rollup, so a
        # fleet postmortem sees the tier working (or rejecting)
        serving_kv = {k: v for k, v in (fams.get("serving") or {}).items()
                      if k in ("pages_spilled", "spill_bytes",
                               "pages_faulted_back", "fault_backs",
                               "fault_back_rejects", "host_tier_bytes")
                      and v}
        ranks[r] = {
            "step": snap.get("step"),
            "steps": steps,
            "time": snap.get("time"),
            "step_wall_mean_s": wall.get("mean"),
            "step_wall_p50_s": wall.get("p50"),
            "step_wall_p95_s": wall.get("p95"),
            "compiles": snap.get("compiles"),
            "compile_s": snap.get("compile_s"),
            "collective_wait_s": snap.get("collective_wait_s"),
            "wait_per_step_s": (
                round(snap.get("collective_wait_s", 0.0) / steps, 6)
                if steps else None),
            "faults": faults,
            "serving_kv": serving_kv,
        }
    report = {"generated_at": round(time.time(), 6),
              "nranks_seen": len(ranks),
              "ranks": ranks, "step_skew": None, "stragglers": []}
    if not ranks:
        return report

    # utility ranks (>= 1000: the fleet router at 1000, the lint CLI at
    # 1001) publish counter snapshots, not training progress — keeping
    # them out of skew/straggler math avoids phantom zero-step laggards
    workers = {r: v for r, v in ranks.items() if r < UTILITY_RANK_BASE}
    if not workers:
        return report
    steps_seen = [v["steps"] for v in workers.values()]
    report["step_skew"] = max(steps_seen) - min(steps_seen)

    # step-frontier lag
    frontier = max(steps_seen)
    for r, v in sorted(workers.items()):
        if frontier - v["steps"] > step_lag:
            report["stragglers"].append({
                "rank": r, "reason": "step_lag",
                "detail": f"rank {r} is at step {v['steps']}, "
                          f"{frontier - v['steps']} behind the group "
                          f"frontier ({frontier})"})

    # collective-wait asymmetry: the rank peers wait ON waits the least
    waits = {r: v["wait_per_step_s"] for r, v in workers.items()
             if v["wait_per_step_s"] is not None}
    if len(waits) >= 2:
        lo_rank = min(waits, key=waits.get)
        gap = max(waits.values()) - waits[lo_rank]
        if gap > straggler_gap_s:
            report["stragglers"].append({
                "rank": lo_rank, "reason": "collective_wait_asymmetry",
                "detail": f"rank {lo_rank} waits {waits[lo_rank]:.3f}s/"
                          f"step at collectives while the slowest-"
                          f"arriving peer waits {max(waits.values()):.3f}"
                          f"s/step — peers are stalled on rank "
                          f"{lo_rank} (gap {gap:.3f}s > "
                          f"{straggler_gap_s:.3f}s threshold)"})
    if warn:
        for s in report["stragglers"]:
            warnings.warn(
                f"telemetry straggler: {s['detail']}", RuntimeWarning,
                stacklevel=2)
    return report


# --------------------------------------------------------------------------
# offline: merge from a telemetry directory
# --------------------------------------------------------------------------

def _steps_from_events(path):
    """Per-step records from one rank's events JSONL (rotated generation
    first so step order is preserved)."""
    steps = []
    for p in (path + ".1", path):
        if not os.path.exists(p):
            continue
        with open(p, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if rec.get("event") == "step":
                    steps.append(rec)
    return steps


def snapshots_from_dir(directory):
    """Reconstruct per-rank snapshots from a telemetry dir: the published
    ``snapshot_rank*.json`` files merged with (and, for step stats,
    recomputed from) the per-step records in ``events_rank*.jsonl``."""
    snaps = {}
    for path in sorted(glob.glob(
            os.path.join(directory, "snapshot_rank*.json"))):
        try:
            with open(path) as f:
                snap = json.load(f)
            snaps[int(snap.get("rank", 0))] = snap
        except (ValueError, OSError):
            continue
    for path in sorted(glob.glob(
            os.path.join(directory, "events_rank*.jsonl"))):
        base = os.path.basename(path)
        try:
            rank = int(base[len("events_rank"):-len(".jsonl")])
        except ValueError:
            continue
        records = _steps_from_events(path)
        if not records and rank not in snaps:
            continue
        snap = snaps.setdefault(rank, {"rank": rank, "families": {}})
        if records:
            # a restarted incarnation resumes from its checkpoint and
            # REPLAYS steps into the same appended log: dedupe by (timer
            # name, step number) — last record wins — so progress and
            # step stats count each training step once, not once per
            # incarnation, while distinct timers in one process (train
            # loop + hapi fit) keep their own step sequences
            by_step = {}
            for rec in records:
                by_step[(str(rec.get("name")), rec.get("step"))] = rec
            steps = [by_step[k] for k in sorted(by_step)]
            walls = sorted(s["wall_s"] for s in steps)

            def pct(p):
                r = max(int(-(-p / 100.0 * len(walls) // 1)), 1)
                return walls[min(r, len(walls)) - 1]

            last = max(steps, key=lambda s: s.get("time") or 0)
            snap.update({
                "time": last.get("time"),
                "step": last.get("step"),
                "steps": len(steps),
                "step_wall": {"count": len(walls),
                              "sum": round(sum(walls), 9),
                              "min": walls[0], "max": walls[-1],
                              "mean": sum(walls) / len(walls),
                              "p50": pct(50), "p95": pct(95)},
            })
            # counter totals: the published snapshot's registry values
            # are authoritative (they include out-of-step compiles and
            # records lost to rotation); the per-step sums — over EVERY
            # record, replays included, since counters reset with the
            # process — only fill in or raise them
            for key, total in (
                    ("compiles",
                     sum(s.get("compiles") or 0 for s in records)),
                    ("compile_s", round(
                        sum(s.get("compile_s") or 0.0
                            for s in records), 6)),
                    ("collective_wait_s", round(
                        sum(s.get("collective_wait_s") or 0.0
                            for s in records), 6))):
                snap[key] = max(snap.get(key) or 0, total)
    return [snaps[r] for r in sorted(snaps)]


def merge_from_dir(directory, straggler_gap_s=None, step_lag=None,
                   warn=False):
    """The offline merge: reconstruct snapshots from a telemetry dir and
    merge them (tools/telemetry_report.py and launch --telemetry)."""
    report = merge(snapshots_from_dir(directory),
                   straggler_gap_s=straggler_gap_s, step_lag=step_lag,
                   warn=warn)
    report["telemetry_dir"] = os.path.abspath(directory)
    return report


# --------------------------------------------------------------------------
# distributed-trace assembly (ISSUE 19)
# --------------------------------------------------------------------------

# which pool owns each lifecycle phase (the attribution rollup is "per
# priority class and role"; the phase → role map IS the role axis)
PHASE_ROLES = {"queue": "router", "prefill": "prefill",
               "parked": "router", "inject": "decode",
               "decode": "decode", "ack": "router",
               "service": "unified"}
PHASE_ORDER = ("queue", "prefill", "parked", "inject", "decode",
               "service", "ack")


def trace_events_from_dir(directory):
    """Every ``trace`` record across the per-rank JSONL files (rotated
    generation first), unsorted.  Unparseable lines are skipped — a
    torn tail from a SIGKILLed writer must not sink the postmortem."""
    events = []
    for path in sorted(glob.glob(
            os.path.join(directory, "events_rank*.jsonl"))):
        for p in (path + ".1", path):
            if not os.path.exists(p):
                continue
            with open(p, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("event") == "trace":
                        events.append(rec)
    return events


def trace_clock_offsets(events):
    """Per-pid clock offset (seconds to ADD to that process's ``t`` to
    land on the router's clock), recovered from the RPC send/recv pairs:
    every ``rpc_recv`` event carries the sender's ``peer_sent`` stamp.

    For a router→replica message, causality says
    ``router_send <= replica_recv + o``, bounding ``o`` from below; for
    a replica→router reply, ``replica_send + o <= router_recv`` bounds
    it from above.  The midpoint of the feasible interval is the
    estimate (tightest when traffic flows both ways); with only one
    bound we sit ON it — the zero-network-delay choice that keeps every
    OBSERVED cross-process span non-negative.  Router pids are the
    reference (offset 0)."""
    lo, hi = {}, {}
    for ev in events:
        if ev.get("name") != "rpc_recv":
            continue
        sent = ev.get("peer_sent")
        if sent is None or ev.get("t") is None:
            continue
        if ev.get("role") == "router":
            peer = ev.get("peer_pid")
            if peer is None:
                continue
            b = ev["t"] - sent
            hi[peer] = b if peer not in hi else min(hi[peer], b)
        else:
            pid = ev.get("pid")
            if pid is None:
                continue
            b = sent - ev["t"]
            lo[pid] = b if pid not in lo else max(lo[pid], b)
    offsets = {}
    for pid in set(lo) | set(hi):
        l, h = lo.get(pid), hi.get(pid)
        if l is not None and h is not None:
            offsets[pid] = (l + h) / 2.0 if l <= h else l
        elif l is not None:
            offsets[pid] = l
        else:
            offsets[pid] = h
    return offsets


def _first(evs, name):
    for ev in evs:
        if ev.get("name") == name:
            return ev
    return None


def _trace_phases(evs):
    """Per-request latency decomposition from one lifecycle's ordered
    events.  Boundaries telescope — queue + prefill + parked + inject +
    decode + ack == ack.t - admit.t exactly (disagg), so the rollup's
    phases SUM to the end-to-end latency instead of approximating it.
    Returns (phases dict, negative_span_count)."""
    t = {}
    for name in ("admit", "dispatch", "park", "ship", "inject",
                 "completion", "ack"):
        ev = _first(evs, name)
        if ev is not None:
            t[name] = ev.get("t_corrected", ev.get("t"))
    phases = {}

    def _span(label, a, b):
        if a in t and b in t:
            phases[label] = round(t[b] - t[a], 6)

    _span("queue", "admit", "dispatch")
    if "park" in t:                      # disaggregated lifecycle
        _span("prefill", "dispatch", "park")
        _span("parked", "park", "ship")
        if "inject" in t:
            _span("inject", "ship", "inject")
            _span("decode", "inject", "completion")
        else:
            _span("decode", "ship", "completion")
    else:                                # unified: one service phase
        _span("service", "dispatch", "completion")
    _span("ack", "completion", "ack")
    negatives = sum(1 for v in phases.values() if v < -1e-6)
    return phases, negatives


def assemble_traces(directory=None, events=None):
    """Stitch per-rank trace events into causally-ordered lifecycles.

    Groups by ``trace_id``, applies per-pid clock-skew offsets from
    :func:`trace_clock_offsets`, orders each lifecycle by corrected
    time (per-process ``seq`` as the same-timestamp tiebreak), and
    decomposes it into phases.  Returns lifecycles sorted by start
    time, each::

        {"trace_id", "request_id", "priority", "hops": [names...],
         "events": [...], "phases": {...}, "e2e_s", "negative_spans",
         "t0"}
    """
    if events is None:
        events = trace_events_from_dir(directory)
    offsets = trace_clock_offsets(events)
    by_tid = {}
    for ev in events:
        tid = ev.get("trace_id")
        if not tid or ev.get("t") is None:
            continue
        off = 0.0 if ev.get("role") == "router" \
            else offsets.get(ev.get("pid"), 0.0)
        ev = dict(ev)
        ev["t_corrected"] = round(ev["t"] + off, 6)
        by_tid.setdefault(tid, []).append(ev)
    lifecycles = []
    for tid, evs in by_tid.items():
        evs.sort(key=lambda e: (e["t_corrected"], e.get("pid") or 0,
                                e.get("seq") or 0))
        phases, negatives = _trace_phases(evs)
        admit = _first(evs, "admit")
        ack = _first(evs, "ack")
        t0 = (admit or evs[0])["t_corrected"]
        t1 = (ack or evs[-1])["t_corrected"]
        lifecycles.append({
            "trace_id": tid,
            "request_id": next((e.get("request_id") for e in evs
                                if e.get("request_id")), None),
            "priority": next((e.get("priority") for e in evs
                              if e.get("priority")), None),
            "hops": [e["name"] for e in evs],
            "events": evs,
            "phases": phases,
            "e2e_s": round(t1 - t0, 6),
            "negative_spans": negatives,
            "t0": t0,
        })
    lifecycles.sort(key=lambda lc: lc["t0"])
    return lifecycles


def trace_attribution(lifecycles):
    """Per-phase latency rollup over assembled lifecycles: p50/p95/p99
    (+ mean, n, owning role) per phase, per priority class and overall,
    plus the dominant phase (largest mean contribution) and the total
    negative-span count (0 is the acceptance bar)."""
    def _rollup(group):
        series = {}
        e2e = []
        for lc in group:
            for ph, v in lc["phases"].items():
                series.setdefault(ph, []).append(v)
            if lc["e2e_s"] is not None:
                e2e.append(lc["e2e_s"])

        def _stats(vals):
            data = sorted(vals)
            return {"n": len(data),
                    "mean": round(sum(data) / len(data), 6),
                    "p50": round(metrics.nearest_rank_percentile(
                        data, 50), 6),
                    "p95": round(metrics.nearest_rank_percentile(
                        data, 95), 6),
                    "p99": round(metrics.nearest_rank_percentile(
                        data, 99), 6)}

        phases = {}
        for ph in PHASE_ORDER:
            if series.get(ph):
                phases[ph] = dict(_stats(series[ph]),
                                  role=PHASE_ROLES.get(ph, "?"))
        out = {"n": len(group), "phases": phases,
               "e2e": _stats(e2e) if e2e else None}
        if phases:
            out["dominant_phase"] = max(
                phases, key=lambda p: phases[p]["mean"])
        return out

    report = {"n": len(lifecycles),
              "negative_spans": sum(lc["negative_spans"]
                                    for lc in lifecycles)}
    if lifecycles:
        report.update(_rollup(lifecycles))
        by_prio = {}
        for lc in lifecycles:
            by_prio.setdefault(lc.get("priority") or "default",
                               []).append(lc)
        report["by_priority"] = {p: _rollup(g)
                                 for p, g in sorted(by_prio.items())}
    return report


def trace_summary(directory):
    """One-line trace posture for a telemetry dir (the report tool's
    ``--traces`` column): lifecycle count, event count, negative spans,
    dominant phase, and how many flight-recorder dumps landed."""
    events = trace_events_from_dir(directory)
    lifecycles = assemble_traces(events=events)
    attr = trace_attribution(lifecycles)
    return {"traces": len(lifecycles), "trace_events": len(events),
            "negative_spans": attr.get("negative_spans", 0),
            "dominant_phase": attr.get("dominant_phase"),
            "flight_dumps": len(glob.glob(
                os.path.join(directory, "flight_*.json")))}


def format_trace_report(attr):
    """Text rendering of a :func:`trace_attribution` rollup."""
    lines = ["== paddle_tpu trace attribution =="]
    lines.append(f"lifecycles: {attr.get('n', 0)}   "
                 f"negative spans: {attr.get('negative_spans', 0)}   "
                 f"dominant phase: {attr.get('dominant_phase', '-')}")

    def _fmt(v):
        return f"{v * 1e3:8.1f}ms" if v is not None else "       -"

    def _block(label, roll):
        e2e = roll.get("e2e")
        if e2e:
            lines.append(f"  [{label}] n={roll['n']} e2e "
                         f"p50={_fmt(e2e['p50'])} p95={_fmt(e2e['p95'])} "
                         f"p99={_fmt(e2e['p99'])}")
        for ph in PHASE_ORDER:
            st = (roll.get("phases") or {}).get(ph)
            if not st:
                continue
            lines.append(f"    {ph:<8} ({st['role']:<7}) n={st['n']:<5} "
                         f"mean={_fmt(st['mean'])} p50={_fmt(st['p50'])} "
                         f"p95={_fmt(st['p95'])} p99={_fmt(st['p99'])}")

    if attr.get("phases"):
        _block("all", attr)
    for prio, roll in sorted((attr.get("by_priority") or {}).items()):
        _block(prio, roll)
    return "\n".join(lines)


def format_report(report):
    """Human-readable text rendering of a merged report."""
    lines = ["== paddle_tpu telemetry report =="]
    if report.get("telemetry_dir"):
        lines.append(f"telemetry dir: {report['telemetry_dir']}")
    lines.append(f"ranks seen: {report['nranks_seen']}   "
                 f"step skew: {report['step_skew']}")
    for r, v in sorted((report.get("ranks") or {}).items()):
        def fmt(x, scale=1e3, suffix="ms"):
            return f"{x * scale:.1f}{suffix}" if x is not None else "-"
        lines.append(
            f"  rank {r}: steps={v['steps']} "
            f"mean={fmt(v['step_wall_mean_s'])} "
            f"p50={fmt(v['step_wall_p50_s'])} "
            f"p95={fmt(v['step_wall_p95_s'])} "
            f"compiles={v.get('compiles')} "
            f"collective_wait={fmt(v.get('collective_wait_s'), 1, 's')}")
        if v.get("faults"):
            faults = ", ".join(f"{k}={n}" for k, n in
                               sorted(v["faults"].items()))
            lines.append(f"          faults: {faults}")
        if v.get("serving_kv"):
            kv = ", ".join(f"{k}={n}" for k, n in
                           sorted(v["serving_kv"].items()))
            lines.append(f"          kv tier: {kv}")
    if report.get("stragglers"):
        lines.append("  STRAGGLERS:")
        for s in report["stragglers"]:
            lines.append(f"    rank {s['rank']} [{s['reason']}]: "
                         f"{s['detail']}")
    else:
        lines.append("  no stragglers detected")
    return "\n".join(lines)
