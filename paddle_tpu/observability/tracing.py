"""Fleet-wide distributed request tracing + the incident flight recorder.

Dapper-style request tracing for the serving fleet (Sigelman et al.,
2010): the router mints a ``trace_id`` at admission and threads it
through every RPC hop — dispatch, step stats, handoff park/ship, KV
inject, retry/re-queue, readopt claims — and both the router and the
replica engines append span events for the hops they own (queue_wait,
prefill_chunk, extract, park, ship, inject, decode_iter, completion,
ack, preemption, fault_back).  Events ride the existing PR-4 timeline
JSONL machinery (``events_rank<R>.jsonl`` under ``PADDLE_TELEMETRY_DIR``)
so one artifact carries steps, spans, serving records AND traces;
``observability.aggregate.assemble_traces`` stitches the per-rank files
back into causally-ordered lifecycles, clock-skew-corrected via the RPC
send/recv pairs each hop records.

Three cost tiers, cheapest first:

* **off (default)** — every :func:`event` call increments the ``trace.*``
  counter family and appends the record to the in-memory flight-recorder
  ring.  No JSON, no I/O.  Per-step hot paths additionally gate on
  :func:`enabled` so the off path there is one env read.
* **``PADDLE_TRACE=1``** — events are also emitted to the timeline JSONL
  log (subject to ``PADDLE_TRACE_SAMPLE``, a deterministic per-trace
  keep fraction), which is what trace assembly and
  ``tools/trace_report.py`` read.
* **incident** — :func:`dump` snapshots the ring (last
  ``PADDLE_TRACE_RING`` events, default 4096) plus the caller's
  in-flight request ids into ``flight_<reason>_*.json`` in the telemetry
  dir.  Called on engine abort, replica SIGKILL detection, router crash
  recovery, load shed, and journal damage — a chaos postmortem names the
  requests that were in flight and their last hop instead of reading a
  bare counter bump.

Clock discipline (the negative-span fix): every record is stamped with
``t`` from :func:`now` — ONE wall anchor plus ``time.monotonic`` deltas,
captured at process start — so a mid-run NTP step never reorders events
within a process.  Cross-process offsets are recovered at assembly time
from the ``rpc_recv`` events' ``peer_sent`` echoes (see
``aggregate.trace_clock_offsets``).

Everything here is stdlib + the in-process metrics registry: no jax, no
numpy — the router, the journal, and the worker bootstrap all import it
before any framework state exists.
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time

from . import metrics, timeline

_ENV_TRACE = "PADDLE_TRACE"
_ENV_RING = "PADDLE_TRACE_RING"
_ENV_SAMPLE = "PADDLE_TRACE_SAMPLE"

_DEFAULT_RING = 4096
_DUMP_MIN_INTERVAL_S = 2.0      # per-reason; a shed storm is one dump

# --------------------------------------------------------------------------
# one coherent clock per process (wall anchor + monotonic deltas)
# --------------------------------------------------------------------------

_WALL_ANCHOR = time.time()
_MONO_ANCHOR = time.monotonic()
_PID = os.getpid()


def now():
    """Coherent wall-clock seconds: one ``time.time()`` anchor captured
    at import plus ``time.monotonic()`` deltas.  Immune to NTP steps —
    two calls in one process NEVER go backwards, so within-process spans
    are non-negative by construction."""
    return _WALL_ANCHOR + (time.monotonic() - _MONO_ANCHOR)


# --------------------------------------------------------------------------
# identity: who is emitting (role/replica), total order (seq)
# --------------------------------------------------------------------------

_seq = itertools.count(1)
_ident = {"role": "engine",
          "replica": os.environ.get("PADDLE_FLEET_REPLICA")}


def seq():
    """Next per-process monotonic sequence number (shared with the
    ``serving_step`` / ``request_complete`` stamps so one process's
    events are totally ordered even at equal timestamps)."""
    return next(_seq)


def set_role(role, replica=None):
    """Label this process's trace events (``router`` / ``replica`` /
    ``supervisor``...).  Workers inherit their replica id from
    ``PADDLE_FLEET_REPLICA``; the router calls this explicitly."""
    _ident["role"] = str(role)
    if replica is not None:
        _ident["replica"] = str(replica)


def role():
    return _ident["role"]


# --------------------------------------------------------------------------
# knobs
# --------------------------------------------------------------------------

def enabled():
    """Full span capture on?  (``PADDLE_TRACE=1``; the off path is
    counters + flight-recorder ring only.)"""
    return os.environ.get(_ENV_TRACE, "0") not in ("", "0", "false", "no")


def ring_size():
    try:
        return max(0, int(os.environ.get(_ENV_RING, str(_DEFAULT_RING))))
    except ValueError:
        return _DEFAULT_RING


def sample_rate():
    try:
        return min(1.0, max(0.0, float(
            os.environ.get(_ENV_SAMPLE, "1.0"))))
    except ValueError:
        return 1.0


def mint():
    """A fresh 16-hex trace id (router calls this once per admission)."""
    import uuid
    return uuid.uuid4().hex[:16]


def sampled(trace_id):
    """Deterministic keep decision: every process (router AND replicas)
    answers identically for the same trace_id, so a sampled lifecycle is
    either complete across all hops or absent — never half-stitched."""
    rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    try:
        frac = int(str(trace_id)[:8], 16) / float(0xFFFFFFFF)
    except ValueError:
        frac = (hash(trace_id) & 0xFFFFFFFF) / float(0xFFFFFFFF)
    return frac < rate


# --------------------------------------------------------------------------
# flight-recorder ring (lock-free-ish: deque appends are atomic; dump
# retries the snapshot if a concurrent append trips the iterator)
# --------------------------------------------------------------------------

_ring_state = {"size": None, "ring": collections.deque(maxlen=_DEFAULT_RING)}
_ring_lock = threading.Lock()
_last_dump = {}                 # reason -> monotonic time of last dump
_dump_lock = threading.Lock()


def _ring():
    n = ring_size()
    if n != _ring_state["size"]:
        with _ring_lock:
            if n != _ring_state["size"]:
                old = _ring_state["ring"]
                _ring_state["ring"] = collections.deque(
                    old, maxlen=n) if n else collections.deque(maxlen=0)
                _ring_state["size"] = n
    return _ring_state["ring"]


def ring_snapshot():
    """A list copy of the ring (oldest first).  Safe under concurrent
    appends: retries the iteration a few times, then falls back to a
    best-effort locked copy."""
    ring = _ring_state["ring"]
    for _ in range(4):
        try:
            return list(ring)
        except RuntimeError:    # deque mutated during iteration
            continue
    with _ring_lock:
        return list(ring)


def _stats_family():
    return metrics.stats_family("trace", {
        "events": 0, "events_emitted": 0, "events_dropped": 0,
        "flight_dumps": 0, "dump_errors": 0})


def stats():
    return dict(_stats_family())


# --------------------------------------------------------------------------
# the event primitive
# --------------------------------------------------------------------------

def event(name, trace_id=None, request_id=None, **attrs):
    """Record one trace span event.

    Always: bumps ``trace.events`` and appends to the flight-recorder
    ring (no I/O — this is the off-by-default cost).  With
    ``PADDLE_TRACE=1`` and a telemetry dir, also emits the record onto
    the timeline JSONL log (sampled per trace id).  Returns the record.
    Exception-safe: tracing must never take down a serving loop."""
    fam = _stats_family()
    fam.inc("events")
    rec = {"event": "trace", "name": str(name),
           "t": round(now(), 6), "seq": next(_seq),
           "pid": _PID, "role": _ident["role"]}
    if _ident["replica"] is not None:
        rec["replica"] = _ident["replica"]
    if trace_id is not None:
        rec["trace_id"] = str(trace_id)
    if request_id is not None:
        rec["request_id"] = str(request_id)
    if attrs:
        rec.update(attrs)
    try:
        _ring().append(rec)
    except Exception:                                      # noqa: BLE001
        pass
    if enabled() and timeline.telemetry_dir() is not None:
        if trace_id is None or sampled(trace_id):
            try:
                timeline.emit(rec)
                fam.inc("events_emitted")
            except Exception:                              # noqa: BLE001
                fam.inc("events_dropped")
    return rec


# --------------------------------------------------------------------------
# incident flight dumps
# --------------------------------------------------------------------------

def dump(reason, inflight=None, extra=None, force=False):
    """Dump the flight recorder to ``flight_<reason>_<pid>_<n>.json`` in
    the telemetry dir: the ring (last-hop evidence), the caller's
    in-flight request ids, and any extra context.  Rate-limited to one
    dump per reason per ~2s unless ``force`` — a shed storm produces one
    postmortem, not thousands.  Returns the path, or None (telemetry
    off / rate-limited / write failed).  Never raises."""
    fam = _stats_family()
    d = timeline.telemetry_dir()
    if not d:
        return None
    key = str(reason)
    with _dump_lock:
        t = time.monotonic()
        last = _last_dump.get(key)
        if not force and last is not None \
                and t - last < _DUMP_MIN_INTERVAL_S:
            return None
        _last_dump[key] = t
    try:
        import json
        payload = {
            "reason": key,
            "t": round(now(), 6),
            "pid": _PID,
            "role": _ident["role"],
            "replica": _ident["replica"],
            "inflight": sorted(str(i) for i in (inflight or [])),
            "extra": extra or {},
            "ring": ring_snapshot(),
        }
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, "flight_%s_%d_%d.json" % (
                "".join(c if (c.isalnum() or c in "-_") else "_"
                        for c in key), _PID, next(_seq)))
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)   # atomic: rotation/readers never see a torn dump
        fam.inc("flight_dumps")
        return path
    except Exception:                                      # noqa: BLE001
        fam.inc("dump_errors")
        return None


def reset_for_tests():
    """Clear ring + dump rate limits (test isolation)."""
    with _ring_lock:
        _ring_state["ring"].clear()
    with _dump_lock:
        _last_dump.clear()
