"""Multi-host coordinator bootstrap — the ONE place that calls
jax.distributed.initialize.

Lives at the package top level (NOT under framework/) because importing
``framework.core`` constructs a PRNG key at module scope, which would
initialize the XLA backend before initialize could run.  Both entry
points route here: ``paddle_tpu/__init__`` (fires when the launcher env
is present, before the package touches jax) and
``distributed.parallel.init_parallel_env`` (direct callers).

Connection failures are RETRIED with exponential backoff: a worker
relaunched by the supervisor (or simply racing a slow coordinator) sees
connection-refused/deadline errors that resolve once the coordinator is
up, so only a bounded window of them — ``PADDLE_BOOTSTRAP_TIMEOUT``
seconds, default 120 — should be fatal.  Genuine misconfiguration (XLA
backend already initialized) raises immediately with the actionable
message.
"""
from __future__ import annotations

import os
import sys
import time

from .observability import metrics as _metrics

_done = [False]

# bootstrap counters, surfaced through profiler.fast_path_summary(); a
# VIEW over the observability registry's "bootstrap" family
_bootstrap_stats = _metrics.stats_family(
    "bootstrap", {"bootstrap_retries": 0})


def bootstrap_stats():
    return dict(_bootstrap_stats)


def _transient(err):
    """Connection-shaped failures a slow/restarting coordinator emits.

    Deliberately broader than collective._is_transient: at BOOTSTRAP a
    deadline/barrier expiry usually means peers have not arrived yet and
    IS worth retrying, whereas mid-training the collectives treat
    deadlines as watchdog events (CollectiveTimeout), never retries —
    keep the policy difference in mind when touching either list."""
    msg = str(err).lower()
    return any(s in msg for s in (
        "connection refused", "failed to connect", "connect failed",
        "deadline exceeded", "timed out", "timeout", "unavailable",
        "connection reset", "broken pipe", "barrier"))


def maybe_init_distributed():
    """Connect to the coordinator iff the launcher env asks for it.
    Idempotent.  Retries transient connection failures with exponential
    backoff until PADDLE_BOOTSTRAP_TIMEOUT (default 120s) elapses, then
    raises with the last error; raises immediately (actionable message)
    if called after XLA backends were already initialized."""
    if _done[0]:
        return
    master = os.environ.get("PADDLE_MASTER")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if not master or nprocs <= 1:
        _done[0] = True
        return
    import jax
    timeout_s = float(os.environ.get("PADDLE_BOOTSTRAP_TIMEOUT", "120"))
    delay = float(os.environ.get("PADDLE_BOOTSTRAP_BACKOFF", "1.0"))
    deadline = time.monotonic() + timeout_s
    last = None
    while True:
        try:
            from jax._src import distributed
            if distributed.global_state.client is not None:
                _done[0] = True
                return                     # a prior attempt got through
        except Exception:                                  # noqa: BLE001
            pass
        # bound each attempt so the retry loop owns the clock: jax's own
        # initialization_timeout defaults to 300s, past our whole budget
        attempt_budget = max(int(min(30.0, deadline - time.monotonic())), 3)
        try:
            jax.distributed.initialize(
                coordinator_address=master,
                num_processes=nprocs,
                process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")),
                initialization_timeout=attempt_budget)
            # latch ONLY on success: a raised bootstrap (timeout, bad
            # config) must stay retryable — latching on entry would make
            # a caught-and-retried failure silently no-op forever after,
            # leaving a world of 1 and divergent same-host replicas
            _done[0] = True
            return
        except ValueError:
            raise                          # malformed config: never retry
        except Exception as e:                             # noqa: BLE001
            msg = str(e)
            if "must be called before" in msg or "already initialized" \
                    in msg.lower():
                raise RuntimeError(
                    "paddle_tpu multi-host bootstrap failed: jax."
                    "distributed.initialize must run before any XLA "
                    "backend use.  Launch through `python -m paddle_tpu."
                    "distributed.launch` (which re-execs the script into "
                    "a clean interpreter), or set PADDLE_MASTER/"
                    "PADDLE_TRAINERS_NUM/PADDLE_TRAINER_ID before "
                    "importing paddle_tpu.") from e
            if not _transient(e):
                raise RuntimeError(
                    f"paddle_tpu multi-host bootstrap failed connecting "
                    f"to coordinator {master}: {e}") from e
            last = e
            try:                           # tear down any half-open client
                jax.distributed.shutdown()
            except Exception:                              # noqa: BLE001
                pass
            if time.monotonic() + delay >= deadline:
                raise RuntimeError(
                    f"paddle_tpu multi-host bootstrap timed out after "
                    f"{timeout_s:.0f}s (PADDLE_BOOTSTRAP_TIMEOUT) waiting "
                    f"for coordinator {master} with "
                    f"{nprocs} processes — last error: {last}.  Check "
                    "that every rank was launched, the coordinator "
                    "host:port is reachable, and PADDLE_TRAINERS_NUM "
                    "matches the real world size; raise "
                    "PADDLE_BOOTSTRAP_TIMEOUT for slow pod bring-up."
                ) from e
            _bootstrap_stats["bootstrap_retries"] += 1
            print(f"# paddle_tpu bootstrap: coordinator {master} not "
                  f"ready ({type(e).__name__}); retrying in {delay:.1f}s",
                  file=sys.stderr, flush=True)
            time.sleep(delay)
            delay = min(delay * 2, 15.0)
