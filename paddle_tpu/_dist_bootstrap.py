"""Multi-host coordinator bootstrap — the ONE place that calls
jax.distributed.initialize.

Lives at the package top level (NOT under framework/) because importing
``framework.core`` constructs a PRNG key at module scope, which would
initialize the XLA backend before initialize could run.  Both entry
points route here: ``paddle_tpu/__init__`` (fires when the launcher env
is present, before the package touches jax) and
``distributed.parallel.init_parallel_env`` (direct callers).
"""
from __future__ import annotations

import os

_done = [False]


def maybe_init_distributed():
    """Connect to the coordinator iff the launcher env asks for it.
    Idempotent.  Raises with an actionable message if called after XLA
    backends were already initialized."""
    if _done[0]:
        return
    _done[0] = True
    master = os.environ.get("PADDLE_MASTER")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if not master or nprocs <= 1:
        return
    import jax
    try:
        jax.distributed.initialize(
            coordinator_address=master,
            num_processes=nprocs,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    except RuntimeError as e:
        raise RuntimeError(
            "paddle_tpu multi-host bootstrap failed: jax.distributed."
            "initialize must run before any XLA backend use.  Launch "
            "through `python -m paddle_tpu.distributed.launch` (which "
            "re-execs the script into a clean interpreter), or set "
            "PADDLE_MASTER/PADDLE_TRAINERS_NUM/PADDLE_TRAINER_ID before "
            "importing paddle_tpu.") from e
