"""paddle.reader — reader-creator decorators.

Re-design of the reference's legacy data pipeline
(ref: python/paddle/reader/decorator.py — map_readers, buffered, shuffle,
batch, compose, chain, firstn, cache, xmap_readers).  A *reader creator*
is a zero-arg callable returning an iterable; decorators wrap creators.
Pure-python host-side plumbing — device transfer happens at the DataLoader
/ feed boundary, so nothing here touches jax.
"""
from __future__ import annotations

import itertools
import queue as queue_mod
import random as random_mod
import threading

__all__ = ["map_readers", "buffered", "shuffle", "batch", "compose",
           "chain", "firstn", "cache", "xmap_readers", "multiprocess_reader"]


def map_readers(func, *readers):
    """Element-wise map over one or more readers zipped together."""
    def reader():
        its = [r() for r in readers]
        for items in zip(*its):
            yield func(*items)
    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of ``buf_size`` items."""
    def new_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                random_mod.shuffle(buf)
                while buf:
                    yield buf.pop()
        random_mod.shuffle(buf)
        while buf:
            yield buf.pop()
    return new_reader


def chain(*readers):
    """Concatenate readers end to end."""
    def reader():
        for r in readers:
            yield from r()
    return reader


def compose(*readers, check_alignment=True):
    """Zip readers into tuples (flattening tuple items, like the ref)."""
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        its = [r() for r in readers]
        for items in itertools.zip_longest(*its):
            if check_alignment and any(i is None for i in items):
                raise RuntimeError("composed readers have different "
                                   "lengths")
            yield sum((make_tuple(i) for i in items), ())
    return reader


def buffered(reader, size):
    """Decouple producer/consumer with a background thread + queue.
    Producer exceptions are forwarded and re-raised at the consumer."""
    end = object()

    def new_reader():
        q = queue_mod.Queue(maxsize=size)

        def fill():
            try:
                for item in reader():
                    q.put((None, item))
            except BaseException as e:                     # noqa: BLE001
                q.put((e, None))
                return
            q.put((None, end))

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            exc, item = q.get()
            if exc is not None:
                raise exc
            if item is end:
                break
            yield item
    return new_reader


def firstn(reader, n):
    def new_reader():
        return itertools.islice(reader(), n)
    return new_reader


def cache(reader):
    """Materialize once; replay from memory afterwards.  Only a pass that
    runs to completion commits the cache (a partially consumed iteration
    must not leave duplicates behind)."""
    data = []
    filled = [False]

    def new_reader():
        if filled[0]:
            yield from data
            return
        this_pass = []
        for item in reader():
            this_pass.append(item)
            yield item
        data[:] = this_pass
        filled[0] = True
    return new_reader


def batch(reader, batch_size, drop_last=False):
    def new_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return new_reader


def xmap_readers(mapper, reader, process_num, buffer_size,
                 order=False):
    """Parallel map with a thread pool (the reference's process pool is a
    poor fit under jit-driven training; threads overlap host-side decode
    with device compute, which is the actual win on TPU)."""
    end = object()

    def new_reader():
        in_q = queue_mod.Queue(buffer_size)
        out_q = queue_mod.Queue(buffer_size)

        def feed():
            for i, item in enumerate(reader()):
                in_q.put((i, item))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                got = in_q.get()
                if got is end:
                    out_q.put(end)
                    return
                i, item = got
                try:
                    out_q.put((i, mapper(item)))
                except BaseException as e:                 # noqa: BLE001
                    # forward the failure, then count this worker as done
                    out_q.put(("error", e))
                    out_q.put(end)
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if order:
            pending = {}
            want = 0
            while finished < process_num:
                got = out_q.get()
                if got is end:
                    finished += 1
                    continue
                i, item = got
                if i == "error":
                    raise item
                pending[i] = item
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                got = out_q.get()
                if got is end:
                    finished += 1
                    continue
                if got[0] == "error":
                    raise got[1]
                yield got[1]
    return new_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Run the readers concurrently on threads, interleaving items as they
    arrive (the reference uses worker processes over pipes; on this runtime
    threads overlap host-side IO with device compute and avoid the fork
    hazards — ``use_pipe`` is accepted for parity).  Reader exceptions are
    forwarded and re-raised at the consumer."""
    end = object()

    def new_reader():
        q = queue_mod.Queue(maxsize=queue_size)

        def run(r):
            try:
                for item in r():
                    q.put((None, item))
            except BaseException as e:                     # noqa: BLE001
                q.put((e, None))
                return
            q.put((None, end))

        for r in readers:
            threading.Thread(target=run, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            exc, item = q.get()
            if exc is not None:
                raise exc
            if item is end:
                finished += 1
                continue
            yield item
    return new_reader
