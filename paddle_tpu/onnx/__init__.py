"""paddle.onnx — model export for interchange.

The reference exports to ONNX via paddle2onnx
(ref: python/paddle/onnx/__init__.py::export).  The TPU-native interchange
format is **StableHLO**: it is what XLA consumes directly, it round-trips
through ``jax.export``, and it carries multi-platform (cpu+tpu) lowerings
in one artifact.  ``paddle.onnx.export`` therefore emits the same
standalone artifact as ``paddle.inference.save_inference_model`` —
``<path>.stablehlo`` + ``<path>.pdmeta`` — loadable by
``paddle.inference.Predictor`` (or raw ``jax.export.deserialize``) in a
fresh process with no Python model class.
"""
from __future__ import annotations

from ..inference.export import save_inference_model


def export(layer, path, input_spec=None, opset_version=None, **configs):
    """Export ``layer`` to the standalone StableHLO artifact at ``path``.

    Mirrors ref paddle.onnx.export(layer, path, input_spec, ...);
    ``opset_version`` is accepted for API parity and ignored (StableHLO
    versions itself).  Returns the artifact's meta manifest."""
    if input_spec is None:
        raise ValueError("input_spec is required to export a model")
    return save_inference_model(path, layer, input_spec, **configs)
