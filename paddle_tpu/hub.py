"""paddle.hub (ref: python/paddle/hub.py).

The reference loads hubconf.py from github/gitee repos or local dirs.
This environment has zero egress, so remote sources raise a clear error;
the LOCAL source path — a directory with ``hubconf.py`` declaring
entrypoints — is fully supported (list/help/load).
"""
from __future__ import annotations

import os
import sys

HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {HUBCONF} in {repo_dir}")
    import importlib.util
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.pop("hubconf", None)
    spec.loader.exec_module(mod)
    return mod


def _resolve(repo_dir, source):
    if source != "local":
        raise RuntimeError(
            "paddle.hub: only source='local' is available in this "
            "zero-egress environment (github/gitee need network)")
    return repo_dir


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoint names exported by the repo's hubconf."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"no entrypoint '{model}' in {repo_dir}")
    return fn.__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Instantiate entrypoint ``model`` from the repo's hubconf."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"no entrypoint '{model}' in {repo_dir}")
    return fn(**kwargs)
