"""Quantization: QAT fake-quant training + post-training quantization.

TPU-native re-design of the reference's slim quantization stack
(ref: python/paddle/fluid/contrib/slim/quantization/
post_training_quantization.py:123 PostTrainingQuantization,
quantization_pass.py QuantizationTransformPass, imperative/qat.py).  The
reference rewrites program graphs to insert fake_quantize ops and emits
cuDNN/MKL-DNN int8 kernels; here:

  * fake quantization is a pure function with a straight-through
    estimator (``jax.custom_vjp`` identity gradient) — it fuses into the
    surrounding XLA program;
  * QAT wraps Linear/Conv2D layers so weights (per-channel absmax) and
    activations (EMA absmax observers) train against quantization noise;
  * deployment runs REAL int8 matmuls on the MXU
    (``lax.dot_general(int8, int8) -> int32`` then rescale), which is
    where TPU int8 throughput comes from;
  * PostTrainingQuantization calibrates observers on sample data without
    training, then converts.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor
from ..ops.dispatch import call
from .. import nn

__all__ = ["fake_quantize", "quant_absmax_scale", "int8_matmul",
           "int8_dynamic_matmul", "QuantConfig", "QAT",
           "PostTrainingQuantization", "QuantedLinear"]


# --------------------------------------------------------------------------
# functional core
# --------------------------------------------------------------------------

def quant_absmax_scale(x, axis=None, bits=8):
    """absmax scale so x/scale fits [-qmax, qmax] (per-tensor, or
    per-channel when axis given — an int keeps that axis, a tuple keeps
    several, e.g. the per-output-channel scales of a stacked [L, K, ...]
    weight keep every axis but the contraction)."""
    v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    qmax = 2.0 ** (bits - 1) - 1
    if axis is None:
        s = jnp.max(jnp.abs(v)) / qmax
    else:
        keep = ((axis,) if isinstance(axis, int) else tuple(axis))
        keep = tuple(a % v.ndim for a in keep)
        red = tuple(i for i in range(v.ndim) if i not in keep)
        s = jnp.max(jnp.abs(v), axis=red, keepdims=False) / qmax
    return jnp.maximum(s, 1e-8)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fake_quant(x, scale, bits):
    qmax = 2.0 ** (bits - 1) - 1
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax)
    return q * scale


def _fq_fwd(x, scale, bits):
    return _fake_quant(x, scale, bits), (x, scale)


def _fq_bwd(bits, res, g):
    # straight-through: pass gradients only where x was inside the clip
    # range (standard QAT STE; the scale gets no gradient — observers own
    # it, matching the reference's moving-average absmax quantizers)
    x, scale = res
    qmax = 2.0 ** (bits - 1) - 1
    inside = (jnp.abs(x / scale) <= qmax).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale)


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


def fake_quantize(x, scale, bits=8, name=None):
    """Quantize-dequantize with STE gradient.  scale: scalar or
    per-channel (broadcastable against x)."""
    return call(lambda xv, sv: _fake_quant(xv, sv, bits), x, scale,
                _name="fake_quantize")


def _int8_mm_core(xv, wv, xs, ws):
    """The MXU int8 GEMM at the heart of :func:`int8_matmul`: quantize x
    with scale ``xs``, ``lax.dot_general(int8, int8) -> int32``, rescale
    to float.  Pure jax (no Tensor/tape) so the serving executables call
    it directly inside jit (:func:`int8_dynamic_matmul`) — one code path
    for the calibrated eager layer and the serving hot loop."""
    xq = jnp.clip(jnp.round(xv / xs), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, wv, (((xv.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (xs * ws)


def int8_matmul(x, w_int8, x_scale, w_scale, name=None):
    """Real int8 GEMM: quantize x per-tensor, int8xint8->int32 on the MXU,
    rescale to float.  w_int8: [in, out] int8; w_scale: [out] or scalar."""
    return call(_int8_mm_core, x, w_int8, x_scale, w_scale,
                _name="int8_matmul")


def int8_dynamic_matmul(x, w_int8, w_scale):
    """W8A8 matmul for the quantized serving path (``quant=
    "int8_dynamic"``): the activation scale is computed IN-GRAPH per
    call (no calibration pass exists at serving time), then the same
    int8xint8 MXU core as :func:`int8_matmul`.  x: [..., in] float;
    w_int8: [in, out]; w_scale: [out]-broadcastable.  Returns fp32.

    The dynamic scale is PER-ROW absmax, not per-tensor: each row of a
    serving batch belongs to a different request (or a pad row), and a
    whole-tensor scale would make one request's logits depend on its
    batchmates — breaking the engine/fleet token-exact retry guarantee
    the moment a retry lands in a different batch mix.  Per-row scales
    are batch-invariant (and tighter)."""
    xs = jnp.maximum(jnp.max(jnp.abs(x), axis=-1, keepdims=True)
                     / 127.0, 1e-8)
    return _int8_mm_core(x, w_int8, xs, w_scale)


# --------------------------------------------------------------------------
# observers + QAT layer wrappers
# --------------------------------------------------------------------------

class AbsmaxObserver:
    """EMA absmax activation observer (ref imperative/qat.py moving-average
    quantizer)."""

    def __init__(self, bits=8, momentum=0.9):
        self.bits = bits
        self.momentum = momentum
        self.scale = None

    def observe(self, x):
        # EMA stays a device scalar: no host sync in the training hot path
        s = quant_absmax_scale(x, bits=self.bits)
        if self.scale is None:
            self.scale = s
        else:
            self.scale = self.momentum * self.scale \
                + (1 - self.momentum) * s
        return self.scale


class QuantConfig:
    """Which layers to quantize and how (ref PostTrainingQuantization's
    quantizable_op_type / weight_bits / activation_bits)."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 quantizable_layer_type=("Linear", "Conv2D"),
                 activation_momentum=0.9):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.quantizable_layer_type = tuple(quantizable_layer_type)
        self.activation_momentum = activation_momentum


class _QATWrapper(nn.Layer):
    """Fake-quant both the weight (per-output-channel) and the input
    activation (EMA per-tensor) around the wrapped layer's forward."""

    def __init__(self, layer, config: QuantConfig):
        super().__init__()
        self.inner = layer
        self._cfg = config
        self._obs = AbsmaxObserver(config.activation_bits,
                                   config.activation_momentum)

    def forward(self, x):
        cfg = self._cfg
        a_scale = self._obs.observe(x.value if isinstance(x, Tensor)
                                    else x)
        x = fake_quantize(x, Tensor(jnp.asarray(a_scale, jnp.float32)),
                          bits=cfg.activation_bits)
        w = self.inner.weight
        axis = w.ndim - 1 if type(self.inner).__name__ == "Linear" else 0
        w_scale = quant_absmax_scale(w, axis=axis, bits=cfg.weight_bits)
        if axis == w.ndim - 1:
            w_scale_b = w_scale[None, :] if w.ndim == 2 else w_scale
        else:
            w_scale_b = w_scale.reshape((-1,) + (1,) * (w.ndim - 1))
        orig = self.inner.weight
        try:
            self.inner.weight = fake_quantize(
                orig, Tensor(w_scale_b), bits=cfg.weight_bits)
            return self.inner(x)
        finally:
            self.inner.weight = orig

    @property
    def weight(self):
        return self.inner.weight


def _wrap_children(model, config, type_names):
    for name, child in list(model.named_children()):
        if type(child).__name__ in type_names:
            setattr(model, name, _QATWrapper(child, config))
        else:
            _wrap_children(child, config, type_names)


class QAT:
    """Quantization-aware training (ref imperative/qat.py::ImperativeQuantAware):
    ``quantize(model)`` wraps layers in place; train as usual; ``convert``
    freezes int8 weights + scales for deployment."""

    def __init__(self, config: QuantConfig | None = None):
        self.config = config or QuantConfig()

    def quantize(self, model):
        _wrap_children(model, self.config,
                       set(self.config.quantizable_layer_type))
        return model

    def convert(self, model):
        """Replace QAT wrappers with real-int8 deploy layers."""
        for name, child in list(model.named_children()):
            if isinstance(child, _QATWrapper):
                inner = child.inner
                if type(inner).__name__ == "Linear":
                    setattr(model, name, QuantedLinear.from_float(
                        inner, child._obs.scale, self.config))
                # Conv stays fake-quant folded: bake quantized weights
                else:
                    w = inner.weight
                    ws = quant_absmax_scale(w, axis=0,
                                            bits=self.config.weight_bits)
                    inner.weight.set_value(fake_quantize(
                        w, Tensor(ws.reshape((-1,) + (1,) * (w.ndim - 1))),
                        bits=self.config.weight_bits))
                    setattr(model, name, inner)
            else:
                self.convert(child)
        return model


class QuantedLinear(nn.Layer):
    """Deploy-time int8 linear: stored int8 weights, MXU int8 GEMM."""

    def __init__(self, w_int8, w_scale, bias, a_scale):
        super().__init__()
        self.w_int8 = w_int8              # jnp int8 [in, out]
        self.w_scale = w_scale            # [out] fp32
        self.bias = bias                  # Tensor | None
        self.a_scale = float(a_scale)

    @classmethod
    def from_float(cls, linear, a_scale, config: QuantConfig):
        if a_scale is None:
            raise ValueError(
                "convert() before calibration: run at least one forward "
                "pass (QAT training or PTQ calibration batches) so the "
                "activation observers have scales")
        w = linear.weight.value
        qmax = 2.0 ** (config.weight_bits - 1) - 1
        ws = quant_absmax_scale(linear.weight, axis=1,
                                bits=config.weight_bits)
        w_int8 = jnp.clip(jnp.round(w / ws[None, :]), -qmax, qmax
                          ).astype(jnp.int8)
        return cls(w_int8, ws, getattr(linear, "bias", None),
                   float(jax.device_get(a_scale)))

    def forward(self, x):
        out = int8_matmul(x, Tensor(self.w_int8),
                          Tensor(jnp.float32(self.a_scale)),
                          Tensor(self.w_scale))
        if self.bias is not None:
            out = out + self.bias
        return out


# --------------------------------------------------------------------------
# post-training quantization
# --------------------------------------------------------------------------

class PostTrainingQuantization:
    """Calibrate activation scales on sample batches, then convert
    (ref post_training_quantization.py:123 — there it drives an Executor
    over a program; here it drives the eager model directly)."""

    def __init__(self, model, config: QuantConfig | None = None):
        self.model = model
        self.config = config or QuantConfig()
        self._qat = QAT(self.config)

    def quantize(self, calib_batches):
        """calib_batches: iterable of model inputs (Tensor or tuple)."""
        self._qat.quantize(self.model)
        import paddle_tpu as paddle
        with paddle.no_grad():
            for batch in calib_batches:
                if isinstance(batch, (tuple, list)):
                    self.model(*batch)
                else:
                    self.model(batch)
        return self._qat.convert(self.model)

    def save_quantized_model(self, path, input_spec=None):
        from ..inference.export import save_inference_model
        if input_spec is None:
            raise ValueError("input_spec required")
        return save_inference_model(path, self.model, input_spec)
