"""DataLoader (ref: python/paddle/fluid/dataloader/dataloader_iter.py).

The reference pipes samples through a C++ BlockingQueue with multiprocess
workers feeding CUDA pinned memory.  Here a thread pool prefetches and
collates into numpy, and the optional C++ ring buffer (runtime/data_ring.cc,
loaded via ctypes) stages batches for overlap with device steps; device
transfer happens lazily on first use so host→HBM copies overlap compute.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from .dataset import BatchSampler, IterableDataset
from ..tensor.tensor import Tensor


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        import jax.numpy as jnp
        return Tensor(jnp.stack([s.value for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return Tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, use_shared_memory=True,
                 prefetch_factor=2, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _fetch(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    def _iter_single(self):
        if self._iterable_mode:
            buf = []
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == self.batch_size:
                    yield self.collate_fn(buf)
                    buf = []
            if buf and not self.drop_last:
                yield self.collate_fn(buf)
            return
        for indices in self.batch_sampler:
            yield self._fetch(indices)

    def _iter_threaded(self):
        """Thread-pool prefetch: workers collate batches ahead of consumption
        (GIL released during numpy/jax host work)."""
        work_q: queue.Queue = queue.Queue()
        done = object()
        out_q: queue.Queue = queue.Queue(
            maxsize=self.prefetch_factor * self.num_workers)
        batches = list(self.batch_sampler)
        order = {}
        lock = threading.Lock()
        next_out = [0]

        for i, b in enumerate(batches):
            work_q.put((i, b))
        for _ in range(self.num_workers):
            work_q.put(done)

        def worker():
            while True:
                item = work_q.get()
                if item is done:
                    out_q.put(done)
                    return
                i, idxs = item
                try:
                    out_q.put((i, self._fetch(idxs)))
                except Exception as e:  # surface in main thread
                    out_q.put((i, e))

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.num_workers)]
        for t in threads:
            t.start()

        finished_workers = 0
        pending = {}
        want = 0
        received = 0
        try:
            while received < len(batches):
                item = out_q.get()
                if item is done:
                    finished_workers += 1
                    continue
                i, data = item
                if isinstance(data, Exception):
                    raise data
                pending[i] = data
                received += 1
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            while want in pending:
                yield pending.pop(want)
                want += 1
        finally:
            for t in threads:
                t.join(timeout=0.1)

    def __iter__(self):
        if self.num_workers and not self._iterable_mode:
            return self._iter_threaded()
        return self._iter_single()
