"""DataLoader (ref: python/paddle/fluid/dataloader/dataloader_iter.py).

The reference pipes samples through a C++ BlockingQueue with multiprocess
workers feeding CUDA pinned memory.  Here a thread pool prefetches and
collates into numpy, and the optional C++ ring buffer (runtime/data_ring.cc,
loaded via ctypes) stages batches for overlap with device steps; device
transfer happens lazily on first use so host→HBM copies overlap compute.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from .dataset import BatchSampler, IterableDataset
from ..observability import metrics as _metrics
from ..observability import timeline as _timeline
from ..tensor.tensor import Tensor

_worker_tls = threading.local()


class WorkerInfo:
    """ref: fluid/dataloader/worker.py::WorkerInfo — identifies the worker
    a sample is being produced in, so IterableDatasets can shard."""

    def __init__(self, id, num_workers, dataset, seed=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, "
                f"num_workers={self.num_workers})")


def get_worker_info():
    """Inside a DataLoader worker: that worker's WorkerInfo; in the main
    process/thread: None (ref: paddle.io.get_worker_info)."""
    return getattr(_worker_tls, "info", None)


# device-prefetch counters, surfaced through paddle_tpu.profiler; a VIEW
# over the observability registry's "prefetch" family (same storage)
_prefetch_stats = _metrics.stats_family(
    "prefetch", {"batches": 0, "hits": 0, "misses": 0, "puts": 0})


def prefetch_stats():
    s = dict(_prefetch_stats)
    n = s["batches"]
    s["hit_ratio"] = round(s["hits"] / n, 4) if n else 0.0
    return s


def reset_prefetch_stats():
    for k in _prefetch_stats:
        _prefetch_stats[k] = 0


def _device_put_leaf(x, sharding):
    """Async host->device transfer of one batch leaf; Tensors rewrap so
    the consumer sees the same pytree types it fed in.  Non-numeric
    leaves (strings, object arrays, python scalars) pass through
    untouched — prefetch must never change the types a collate_fn
    produced."""
    import jax
    if isinstance(x, Tensor):
        v = x.value
        out = jax.device_put(v, sharding) if sharding is not None else v
        if out is v:
            return x
        t = Tensor(out)
        t.stop_gradient = x.stop_gradient
        return t
    if isinstance(x, np.ndarray) and not x.dtype.hasobject \
            and x.dtype.kind not in "USV":
        return jax.device_put(x, sharding)
    return x


def _leaf_sharding(x, mesh):
    """Shard the batch's leading axis over the mesh's dp axis when it
    divides evenly; replicate otherwise.  No mesh: default device."""
    import jax
    from ..framework.jax_compat import named_sharding, partition_spec as P
    if mesh is None:
        return jax.devices()[0]
    shape = getattr(x, "shape", ())
    if "dp" in mesh.axis_names and mesh.shape["dp"] > 1 and shape \
            and shape[0] % mesh.shape["dp"] == 0:
        return named_sharding(mesh, P("dp"))
    return named_sharding(mesh, P())


def prefetch_to_device(iterable, depth=1, mesh=None):
    """Wrap a batch iterator so each batch's host->device transfer is
    launched ``depth`` batches AHEAD of consumption: step N's H2D overlaps
    step N-1's compute instead of sitting on the critical path (ref: the
    CUDA pinned-memory double buffer in fluid/reader/buffered_reader.cc).

    Batches are pytrees of Tensors / numpy arrays.  With an active device
    mesh (paddle_tpu.parallel mesh_scope, or ``mesh=``), leaves whose
    leading axis divides the 'dp' axis are device_put SHARDED over it.
    A batch whose transfer finished before the consumer asked counts as a
    prefetch hit; one the consumer had to wait on counts as a miss
    (profiler.fast_path_summary()['prefetch'])."""
    import collections
    import jax

    if mesh is None:
        from ..parallel import mesh as mesh_mod
        mesh = mesh_mod.get_mesh()
    depth = max(int(depth), 1)

    def _put(batch):
        _prefetch_stats["puts"] += 1
        with _timeline.span("h2d_prefetch"):
            return jax.tree_util.tree_map(
                lambda x: _device_put_leaf(x, _leaf_sharding(x, mesh)),
                batch, is_leaf=lambda x: isinstance(x, Tensor))

    def _ready(batch):
        leaves = jax.tree_util.tree_leaves(
            batch, is_leaf=lambda x: isinstance(x, Tensor))
        for leaf in leaves:
            v = leaf.value if isinstance(leaf, Tensor) else leaf
            ready = getattr(v, "is_ready", None)
            if ready is not None and not ready():
                return False
        return True

    def _gen():
        it = iter(iterable)
        buf = collections.deque()
        try:
            while len(buf) < depth:
                buf.append(_put(next(it)))
        except StopIteration:
            pass
        while buf:
            batch = buf.popleft()
            _prefetch_stats["batches"] += 1
            _prefetch_stats["hits" if _ready(batch) else "misses"] += 1
            try:
                buf.append(_put(next(it)))
            except StopIteration:
                pass
            yield batch

    return _gen()


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        import jax.numpy as jnp
        return Tensor(jnp.stack([s.value for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return Tensor(np.asarray(batch))


def _numpy_collate(batch):
    """Collate into a pytree of numpy arrays (native staging path)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s.numpy()) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return type(sample)(_numpy_collate([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: _numpy_collate([b[k] for b in batch]) for k in sample}
    return np.asarray(batch)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, use_shared_memory=True,
                 prefetch_factor=2, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_native_ring=None,
                 prefetch_to_device=False):
        self.dataset = dataset
        # False: off.  True / int N: keep N batches device_put ahead of
        # consumption (sharded over the active mesh's dp axis when
        # present) so H2D overlaps the previous step's compute
        self.prefetch_to_device = prefetch_to_device
        self.collate_fn = collate_fn or default_collate_fn
        self._default_collate = collate_fn is None
        self.num_workers = num_workers
        self.use_native_ring = use_native_ring
        self.prefetch_factor = max(prefetch_factor, 2)
        # reference contract args: timeout bounds each batch wait (0 =
        # wait forever), worker_init_fn runs once in every worker with
        # its id.  persistent_workers is accepted for API parity; workers
        # here are threads (re-created per epoch at negligible cost), so
        # persistence has nothing to buy.
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
        # resumable iteration state (captured by CheckpointManager):
        # epochs completed, batches handed out this epoch, and a pending
        # skip installed by set_state_dict for the next __iter__
        self._epoch = 0
        self._batch_index = 0
        self._resume_skip = 0
        self._epoch_rng_state = None   # np RNG as of this epoch's START

    # ------------------------------------------------- resumable state
    def state_dict(self):
        """Iteration position for crash-consistent resume: completed
        epochs, batches already handed to the consumer this epoch, and
        the numpy global RNG state as of the CURRENT EPOCH'S START —
        what a shuffling sampler (RandomSampler without an explicit
        generator) drew this epoch's permutation from, so a resumed
        epoch re-draws the SAME order and the skip lands on the right
        batches.  Pass the loader to ``CheckpointManager.save(...,
        dataloader=loader)`` to capture it with the training state."""
        rng = (self._epoch_rng_state if self._epoch_rng_state is not None
               else np.random.get_state())
        return {"epoch": self._epoch, "batch_index": self._batch_index,
                "np_rng_state": rng}

    def set_state_dict(self, state):
        """Rewind to a captured position: the next ``__iter__`` skips the
        first ``batch_index`` batches (map-style datasets skip at the
        sampler level without fetching data; iterable datasets must
        consume and discard) and the numpy RNG stream is restored so a
        shuffling epoch replays the same order."""
        self._epoch = int(state.get("epoch", 0))
        self._resume_skip = int(state.get("batch_index", 0))
        # reflect the restored position immediately: a state_dict taken
        # BEFORE the next __iter__ must not report batch 0 (losing the
        # skip and double-training the replayed batches on the next
        # resume)
        self._batch_index = self._resume_skip
        # the restored stream is also this (resumed) epoch's start state:
        # a state_dict taken before the next __iter__ must hand back the
        # restored RNG, not a pre-restore epoch's stale capture
        self._epoch_rng_state = state.get("np_rng_state")
        if state.get("np_rng_state") is not None:
            np.random.set_state(state["np_rng_state"])

    def _track(self, it, skip):
        """Count batches handed out (AFTER any device prefetch, so the
        count is consumer truth, not prefetch depth) and roll the epoch
        counter when the iterator drains."""
        self._batch_index = skip
        for batch in it:
            self._batch_index += 1
            yield batch
        self._epoch += 1
        self._batch_index = 0
        # the epoch is over: a between-epoch state_dict must capture the
        # CURRENT stream (next epoch draws fresh), not this epoch's start
        # — rewinding would make the resumed epoch repeat this one's
        # shuffle order
        self._epoch_rng_state = None

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __call__(self):
        # fluid-era loops spell `for batch in loader():` (the reader-
        # factory convention) — calling yields the same iterator
        return iter(self)

    def _fetch(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    def _iter_single(self, skip=0):
        if self._iterable_mode:
            buf = []
            emitted = 0
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == self.batch_size:
                    emitted += 1
                    if emitted > skip:     # resume: discard replayed ones
                        yield self.collate_fn(buf)
                    buf = []
            if buf and not self.drop_last and emitted + 1 > skip:
                yield self.collate_fn(buf)
            return
        import itertools
        for indices in itertools.islice(iter(self.batch_sampler),
                                        skip, None):
            yield self._fetch(indices)

    def _iter_threaded(self, skip=0):
        """Thread-pool prefetch: workers collate batches ahead of consumption
        (GIL released during numpy/jax host work).

        Work is SUBMITTED lazily — at most prefetch_factor*num_workers
        batches outstanding — so one slow batch cannot make the ordered-
        yield reorder buffer absorb the whole epoch (pending is bounded
        by the outstanding window)."""
        work_q: queue.Queue = queue.Queue()
        done = object()
        out_q: queue.Queue = queue.Queue()
        batches = list(self.batch_sampler)[skip:]
        window = self.prefetch_factor * self.num_workers

        def worker(wid):
            _worker_tls.info = WorkerInfo(wid, self.num_workers,
                                          self.dataset)
            if self.worker_init_fn is not None:
                try:
                    self.worker_init_fn(wid)
                except Exception as e:                     # noqa: BLE001
                    out_q.put((-1, e))
                    return
            while True:
                item = work_q.get()
                if item is done:
                    out_q.put(done)
                    return
                i, idxs = item
                try:
                    out_q.put((i, self._fetch(idxs)))
                except Exception as e:  # surface in main thread
                    out_q.put((i, e))

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()

        submitted = 0

        def refill():
            nonlocal submitted
            # submitted - want counts BOTH in-flight and reorder-buffered
            # batches: subtracting len(pending) here would re-open the
            # window as completions buffer up behind a straggler, letting
            # `pending` absorb the epoch
            while (submitted < len(batches)
                   and submitted - want < window):
                work_q.put((submitted, batches[submitted]))
                submitted += 1
            if submitted == len(batches):
                for _ in range(self.num_workers):
                    work_q.put(done)

        pending = {}
        want = 0
        received = 0
        filled_done = False
        try:
            refill()
            if submitted == len(batches):
                filled_done = True
            while received < len(batches):
                try:
                    item = out_q.get(
                        timeout=self.timeout if self.timeout else None)
                except queue.Empty:
                    raise RuntimeError(
                        f"DataLoader worker produced no batch within "
                        f"timeout={self.timeout}s")
                if item is done:
                    continue
                i, data = item
                if isinstance(data, Exception):
                    raise data
                pending[i] = data
                received += 1
                while want in pending:
                    yield pending.pop(want)
                    want += 1
                if not filled_done:
                    refill()
                    if submitted == len(batches):
                        filled_done = True
            while want in pending:
                yield pending.pop(want)
                want += 1
        finally:
            if not filled_done:
                for _ in range(self.num_workers):
                    work_q.put(done)
            for t in threads:
                t.join(timeout=0.1)

    def _iter_native_ring(self, skip=0):
        """Native staging path (ref: C++ BlockingQueue reader, paddle/fluid/
        operators/reader/blocking_queue.h): workers collate to numpy and
        gather each batch into ONE C++ pool slab (memcpy with the GIL
        released), the bounded ring backpressures producers, and the
        consumer wraps popped views into Tensors — host staging overlaps
        the device step."""
        import jax
        from jax.tree_util import tree_flatten, tree_unflatten

        from .. import runtime

        batches = list(self.batch_sampler)[skip:]
        ring = runtime.DataRing(
            capacity=self.prefetch_factor * self.num_workers)
        treedefs = {}
        td_lock = threading.Lock()
        errors = []
        done = object()
        work_q: queue.Queue = queue.Queue()

        def collate(idxs):
            samples = [self.dataset[i] for i in idxs]
            if self._default_collate:
                tree = _numpy_collate(samples)
            else:
                tree = jax.tree.map(
                    lambda x: np.asarray(x.numpy() if isinstance(x, Tensor)
                                         else x), self.collate_fn(samples))
            leaves, td = tree_flatten(tree)
            for leaf in leaves:
                if not isinstance(leaf, np.ndarray) or leaf.dtype.hasobject:
                    raise TypeError(
                        "native-ring DataLoader requires numeric array "
                        f"batches, got dtype={getattr(leaf, 'dtype', type(leaf))}; "
                        "pass use_native_ring=False for object batches")
            return leaves, td

        def worker(wid):
            _worker_tls.info = WorkerInfo(wid, self.num_workers,
                                          self.dataset)
            if self.worker_init_fn is not None:
                try:
                    self.worker_init_fn(wid)
                except Exception as e:                     # noqa: BLE001
                    errors.append(e)
                    ring.close()
                    return
            while True:
                item = work_q.get()
                if item is done:
                    return
                i, idxs = item
                try:
                    leaves, td = collate(idxs)
                    with td_lock:
                        treedefs[i] = (td, len(leaves))
                    rc = ring.push(leaves, i)
                    if rc == runtime.DataRing.CLOSED:
                        return       # consumer shut down under us
                    if rc != 0:
                        raise MemoryError(
                            f"native ring push failed (code {rc})")
                except Exception as e:
                    errors.append(e)
                    ring.close()
                    return

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()

        # lazy submission bounds the reorder buffer: the ring caps how
        # far producers run ahead, but the consumer must keep draining
        # it (a full ring would block the straggler batch's producer),
        # so `pending` is bounded by capping OUTSTANDING work instead
        window = self.prefetch_factor * self.num_workers
        pending = {}
        want = 0
        submitted = 0
        sent_done = False

        def refill():
            nonlocal submitted, sent_done
            while (submitted < len(batches)
                   and submitted - want < window):
                work_q.put((submitted, batches[submitted]))
                submitted += 1
            if submitted == len(batches) and not sent_done:
                sent_done = True
                for _ in range(self.num_workers):
                    work_q.put(done)

        try:
            refill()
            while want < len(batches):
                if want in pending:
                    yield pending.pop(want)
                    want += 1
                    refill()
                    continue
                try:
                    got = ring.pop(
                        timeout_ms=int(self.timeout * 1000)
                        if self.timeout else -1)
                except TimeoutError:
                    raise RuntimeError(
                        f"DataLoader worker produced no batch within "
                        f"timeout={self.timeout}s")
                if got is None:        # closed: error or all done
                    if errors:
                        raise errors[0]
                    break
                views, tag = got
                td, _ = treedefs.pop(tag)
                # Tensor() copies out of the slab (host->device put), so the
                # views may be recycled after this line
                tree = tree_unflatten(td, [Tensor(v.copy()) for v in views])
                pending[tag] = tree
            while want in pending:
                yield pending.pop(want)
                want += 1
            if errors:
                raise errors[0]
        finally:
            ring.close()
            if not sent_done:          # unblock workers parked on get()
                for _ in range(self.num_workers):
                    work_q.put(done)
            for t in threads:
                t.join(timeout=2.0)
            # destroy is race-safe even under a live producer: the C
            # handle is erased (later ops fail cleanly as closed) and the
            # native object parks in a graveyard with its queued slabs
            # released and pool trimmed — a stuck worker costs at most
            # its one in-flight slab, not a 30s shutdown stall
            ring.destroy()

    def _iter_iterable_workers(self, skip=0):
        """Multi-worker IterableDataset: each worker thread iterates the
        dataset under its own WorkerInfo (datasets shard themselves via
        get_worker_info, reference semantics) and batches locally."""
        out_q: queue.Queue = queue.Queue(
            maxsize=self.prefetch_factor * self.num_workers)
        done = object()

        def worker(wid):
            _worker_tls.info = WorkerInfo(wid, self.num_workers,
                                          self.dataset)
            try:
                buf = []
                for sample in self.dataset:
                    buf.append(sample)
                    if len(buf) == self.batch_size:
                        out_q.put(self.collate_fn(buf))
                        buf = []
                if buf and not self.drop_last:
                    out_q.put(self.collate_fn(buf))
            except Exception as e:
                out_q.put(e)
            finally:
                out_q.put(done)

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        finished = 0
        dropped = 0
        while finished < self.num_workers:
            item = out_q.get()
            if item is done:
                finished += 1
                continue
            if isinstance(item, Exception):
                raise item
            if dropped < skip:
                dropped += 1
                continue
            yield item
        for t in threads:
            t.join(timeout=0.1)

    def __iter__(self):
        skip = self._resume_skip
        self._resume_skip = 0
        # position resets EAGERLY: a state_dict between iter() and the
        # first next() must report this epoch's position (skip), not a
        # previous abandoned epoch's batch index (_track's own reset
        # only runs at the generator's first next())
        self._batch_index = skip
        # record the RNG the sampler is about to draw from: a mid-epoch
        # state_dict must hand back THIS state (not the post-draw one) so
        # the resumed epoch replays the same shuffled order
        self._epoch_rng_state = np.random.get_state()
        if self.num_workers and self._iterable_mode:
            # worker interleaving is nondeterministic here; a resume skip
            # drops the first `skip` produced batches, best effort
            it = self._iter_iterable_workers(skip)
        elif self.num_workers and not self._iterable_mode:
            use_ring = self.use_native_ring
            if use_ring is None:
                # auto mode must not stall the first epoch on a C++ compile:
                # only take the native path when the library is already built
                from .. import runtime
                use_ring = runtime.is_prebuilt()
            it = (self._iter_native_ring(skip) if use_ring
                  else self._iter_threaded(skip))
        else:
            it = self._iter_single(skip)
        if self.prefetch_to_device:
            depth = (1 if self.prefetch_to_device is True
                     else int(self.prefetch_to_device))
            it = prefetch_to_device(it, depth=depth)
        return self._track(it, skip)
