"""paddle.save / paddle.load (ref: python/paddle/framework/io.py)."""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..tensor.tensor import Tensor, Parameter


def _to_storable(obj):
    if isinstance(obj, Parameter):
        return {"__param__": obj.numpy(), "name": obj.name,
                "trainable": obj.trainable}
    if isinstance(obj, Tensor):
        return {"__tensor__": obj.numpy(), "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_storable(v) for v in obj)
    return obj


def _from_storable(obj):
    if isinstance(obj, dict):
        if "__param__" in obj:
            p = Parameter(obj["__param__"], name=obj.get("name"),
                          trainable=obj.get("trainable", True))
            return p
        if "__tensor__" in obj:
            return Tensor(obj["__tensor__"], name=obj.get("name"))
        return {k: _from_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_storable(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_storable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        data = pickle.load(f)
    return _from_storable(data)
