"""paddle_tpu.io (ref: python/paddle/io/__init__.py)."""
from .dataset import (Dataset, IterableDataset, TensorDataset, ComposeDataset,
                      ChainDataset, ConcatDataset, Subset, random_split,
                      Sampler, SequenceSampler, RandomSampler,
                      WeightedRandomSampler, BatchSampler,
                      DistributedBatchSampler)
from .dataloader import (DataLoader, default_collate_fn, get_worker_info,
                         WorkerInfo, prefetch_to_device)
from .serialization import save, load
