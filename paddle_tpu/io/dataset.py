"""Datasets and samplers (ref: python/paddle/io/ → fluid/dataloader/)."""
from __future__ import annotations

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset has no __getitem__")

    def __len__(self):
        # TypeError (not RuntimeError) so list()/length_hint treat it as
        # "no length available" instead of propagating
        raise TypeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, index):
        return tuple(t[index] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (tuple, list)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        if idx < 0:                      # torch/reference semantics
            if idx < -len(self):
                raise IndexError(idx)
            idx += len(self)
        ds = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds == 0 else int(self.cum[ds - 1])
        return self.datasets[ds][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def _rng_from_generator(generator):
    """A numpy RandomState honoring an explicit generator: an int seed, a
    numpy Generator/RandomState, or a paddle-style generator exposing
    initial_seed()/get_state(); None falls back to global np.random."""
    if generator is None:
        return np.random
    if isinstance(generator, (int, np.integer)):
        return np.random.RandomState(int(generator))
    if isinstance(generator, (np.random.RandomState, np.random.Generator)):
        return generator
    for attr in ("initial_seed", "seed"):
        fn = getattr(generator, attr, None)
        if callable(fn):
            try:
                return np.random.RandomState(int(fn()) % (2 ** 32))
            except Exception:                              # noqa: BLE001
                break
    return np.random


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        # paddle 2.x also supports fractions; keep ints strict
        raise ValueError("sum of lengths != dataset size")
    perm = _rng_from_generator(generator).permutation(total)
    out = []
    off = 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = _rng_from_generator(self.generator)
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shard batches across data-parallel ranks (ref: python/paddle/io/
    DistributedBatchSampler → fleet).  On TPU the 'ranks' are mesh dp slices;
    the loader yields each rank its contiguous shard."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None \
            else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
            self.epoch += 1
        # pad to be evenly divisible; TILE when pad > n (tiny datasets on
        # many ranks) so every rank gets num_samples entries — unequal
        # counts would deadlock the data-parallel collectives
        pad = self.total_size - n
        if pad > 0:
            reps = int(np.ceil(pad / max(n, 1)))
            indices = np.concatenate([indices] + [indices] * reps)[
                :self.total_size]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(int(idx))
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
