"""Native host runtime (C++): staging memory pool + data ring.

TPU-native analogue of the reference's C++ data path (ref:
paddle/fluid/operators/reader/blocking_queue.h, paddle/fluid/memory/
allocation/auto_growth_best_fit_allocator.cc).  The compute path is XLA;
what stays native is the host side: batch staging buffers drawn from a
size-class auto-growth pool, and a bounded blocking ring that overlaps
worker collation + memcpy (GIL released via ctypes) with device steps.

Degrades gracefully: if no C++ toolchain is available, is_available() is
False and io.DataLoader falls back to its pure-Python queue.
"""
from __future__ import annotations

import ctypes
import threading

import numpy as np

from .build import build as _build

_lib = None
_lib_err = None
_lock = threading.Lock()


def _load():
    global _lib, _lib_err
    with _lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        path = _build()
        if path is None:
            _lib_err = "no C++ toolchain"
            return None
        lib = ctypes.CDLL(path)
        lib.ptpu_pool_create.restype = ctypes.c_int64
        lib.ptpu_pool_alloc.restype = ctypes.c_void_p
        lib.ptpu_pool_alloc.argtypes = [ctypes.c_int64, ctypes.c_uint64]
        lib.ptpu_pool_free.argtypes = [ctypes.c_int64, ctypes.c_void_p]
        lib.ptpu_pool_destroy.argtypes = [ctypes.c_int64]
        lib.ptpu_pool_stats.argtypes = [ctypes.c_int64,
                                        ctypes.POINTER(ctypes.c_uint64)]
        lib.ptpu_ring_create.restype = ctypes.c_int64
        lib.ptpu_ring_create.argtypes = [ctypes.c_int]
        lib.ptpu_ring_destroy.argtypes = [ctypes.c_int64]
        lib.ptpu_ring_push_gather.restype = ctypes.c_int
        lib.ptpu_ring_push_gather.argtypes = [
            ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int]
        lib.ptpu_ring_pop.restype = ctypes.c_int
        lib.ptpu_ring_pop.argtypes = [
            ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int]
        lib.ptpu_ring_release.argtypes = [ctypes.c_int64, ctypes.c_void_p]
        lib.ptpu_ring_close.argtypes = [ctypes.c_int64]
        lib.ptpu_ring_size.restype = ctypes.c_int
        lib.ptpu_ring_size.argtypes = [ctypes.c_int64]
        lib.ptpu_ring_stats.argtypes = [ctypes.c_int64,
                                        ctypes.POINTER(ctypes.c_uint64)]
        lib.ptpu_preprocess_u8_nhwc_to_f32_nchw.restype = ctypes.c_int
        lib.ptpu_preprocess_u8_nhwc_to_f32_nchw.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_float,
            ctypes.c_void_p, ctypes.c_int]
        lib.ptpu_wp_create.restype = ctypes.c_int64
        lib.ptpu_wp_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                       ctypes.c_char_p]
        lib.ptpu_wp_destroy.argtypes = [ctypes.c_int64]
        lib.ptpu_wp_encode.restype = ctypes.c_int64
        lib.ptpu_wp_encode.argtypes = [
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
        _lib = lib
        return _lib


def is_available():
    """True if the native library is loadable (builds it on first call)."""
    return _load() is not None


def is_prebuilt():
    """True if the .so already exists and loads — never triggers a compile."""
    from . import build as _b
    import os
    if not (os.path.exists(_b.LIB)
            and os.path.getmtime(_b.LIB) >= os.path.getmtime(_b.SRC)):
        return False
    return _load() is not None


class HostMemoryPool:
    """Size-class auto-growth host allocator with statistics.

    Analogue of the reference's AutoGrowthBestFitAllocator for host staging
    memory (device memory is managed by XLA/libtpu on TPU).
    """

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native runtime unavailable: {_lib_err}")
        self._lib = lib
        self._h = lib.ptpu_pool_create()

    def alloc(self, nbytes: int) -> int:
        p = self._lib.ptpu_pool_alloc(self._h, nbytes)
        if not p:
            raise MemoryError(f"pool alloc of {nbytes} bytes failed")
        return p

    def free(self, ptr: int):
        self._lib.ptpu_pool_free(self._h, ptr)

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 6)()
        self._lib.ptpu_pool_stats(self._h, out)
        keys = ("reserved", "in_use", "peak_in_use", "alloc_count",
                "grow_count", "free_count")
        return dict(zip(keys, [int(v) for v in out]))

    def close(self):
        if self._h:
            self._lib.ptpu_pool_destroy(self._h)
            self._h = 0

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class DataRing:
    """Bounded blocking ring of staged batches.

    push(arrays, tag) gathers a batch's numpy arrays into one native slab
    (single GIL-released memcpy pass) and blocks while the ring is full;
    pop() returns (views, tag) where views are zero-copy numpy views into
    the slab — consume (e.g. device-put) then the slab is recycled on the
    next pop via deferred release.
    """

    CLOSED, TIMEOUT, OOM = -1, -2, -3

    def __init__(self, capacity: int = 8):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native runtime unavailable: {_lib_err}")
        self._lib = lib
        self._h = lib.ptpu_ring_create(capacity)
        self._meta = {}           # tag -> per-array (shape, dtype, nbytes)
        self._meta_lock = threading.Lock()
        # deferred slab free: serialized by _pop_lock — the C++ ring is
        # MPMC, but the zero-copy views contract ("valid until the next
        # pop") forces one pop at a time through THIS wrapper, else a
        # second consumer's pop would recycle a slab whose views the
        # first consumer is still reading
        self._pending_release = None
        self._pop_lock = threading.Lock()

    def push(self, arrays, tag: int, timeout_ms: int = -1) -> int:
        arrs = [np.ascontiguousarray(a) for a in arrays]
        n = len(arrs)
        srcs = (ctypes.c_void_p * n)(
            *[a.ctypes.data for a in arrs])
        lens = (ctypes.c_uint64 * n)(*[a.nbytes for a in arrs])
        with self._meta_lock:
            self._meta[tag] = [(a.shape, a.dtype, a.nbytes) for a in arrs]
        rc = self._lib.ptpu_ring_push_gather(self._h, srcs, lens, n,
                                             tag, timeout_ms)
        if rc != 0:
            with self._meta_lock:
                self._meta.pop(tag, None)
        return rc

    def pop(self, timeout_ms: int = -1):
        """Returns (list_of_array_views, tag) or None when closed+drained.

        The views alias native memory that is recycled on the NEXT pop();
        copy (or device-put) before then.  Pops through this wrapper are
        serialized (see _pop_lock) so that contract is enforceable.
        """
        with self._pop_lock:
            if self._pending_release is not None:
                self._lib.ptpu_ring_release(self._h, self._pending_release)
                self._pending_release = None
            ptr = ctypes.c_void_p()
            ln = ctypes.c_uint64()
            tag = ctypes.c_uint64()
            rc = self._lib.ptpu_ring_pop(self._h, ctypes.byref(ptr),
                                         ctypes.byref(ln),
                                         ctypes.byref(tag), timeout_ms)
            if rc == self.CLOSED:
                return None
            if rc == self.TIMEOUT:
                raise TimeoutError("DataRing.pop timed out")
            with self._meta_lock:
                meta = self._meta.pop(int(tag.value))
            buf = (ctypes.c_char * ln.value).from_address(ptr.value)
            flat = np.frombuffer(buf, dtype=np.uint8)
            views, off = [], 0
            for shape, dtype, nbytes in meta:
                views.append(
                    flat[off:off + nbytes].view(dtype).reshape(shape))
                off += nbytes
            self._pending_release = ptr.value
            return views, int(tag.value)

    def close(self):
        self._lib.ptpu_ring_close(self._h)

    def size(self) -> int:
        return self._lib.ptpu_ring_size(self._h)

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 8)()
        self._lib.ptpu_ring_stats(self._h, out)
        keys = ("pushed", "popped", "reserved", "in_use", "peak_in_use",
                "alloc_count", "grow_count", "free_count")
        return dict(zip(keys, [int(v) for v in out]))

    def destroy(self):
        if self._h:
            if self._pending_release is not None:
                self._lib.ptpu_ring_release(self._h, self._pending_release)
                self._pending_release = None
            self._lib.ptpu_ring_destroy(self._h)
            self._h = 0

    def __del__(self):  # pragma: no cover
        try:
            self.destroy()
        except Exception:
            pass


_host_pool = None


def host_memory_pool() -> HostMemoryPool:
    """Process-wide staging pool (paddle.device.cuda.memory_* analogue)."""
    global _host_pool
    if _host_pool is None:
        _host_pool = HostMemoryPool()
    return _host_pool


def host_memory_stats() -> dict:
    return host_memory_pool().stats()


def preprocess_images(images, mean, std, scale=1.0 / 255.0, n_threads=0):
    """Fused u8 NHWC -> normalized f32 NCHW batch preprocess in native code
    (the reference does per-image normalize/to_tensor in Python workers;
    ref python/paddle/vision/transforms/functional.py).

    images: uint8 array [N, H, W, C] or list of [H, W, C] arrays;
    mean/std: per-channel (post-scale units, like transforms.Normalize);
    returns float32 [N, C, H, W].  Falls back to numpy when the native
    library is unavailable.
    """
    import os

    if isinstance(images, np.ndarray):
        assert images.ndim == 4, images.shape
        images = [images[i] for i in range(images.shape[0])]
    if not images:
        raise ValueError("preprocess_images: empty batch")
    for a in images:
        if np.asarray(a).dtype != np.uint8:
            raise TypeError("preprocess_images expects uint8 images, got "
                            f"{np.asarray(a).dtype} (normalize raw pixels, "
                            "not already-scaled floats)")
    imgs = [np.ascontiguousarray(a, np.uint8) for a in images]
    n = len(imgs)
    h, w, c = imgs[0].shape
    for a in imgs:
        if a.shape != (h, w, c):
            raise ValueError("preprocess_images: all images must share one "
                             f"shape; got {a.shape} vs {(h, w, c)}")
    mean = np.asarray(mean, np.float32).reshape(c)
    std = np.asarray(std, np.float32).reshape(c)

    lib = _load()
    if lib is None:
        batch = np.stack(imgs).astype(np.float32) * scale
        batch = (batch - mean) / std
        return np.ascontiguousarray(batch.transpose(0, 3, 1, 2))

    out = np.empty((n, c, h, w), np.float32)
    srcs = (ctypes.c_void_p * n)(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in imgs])
    inv_std = np.ascontiguousarray(1.0 / std, np.float32)
    mean_c = np.ascontiguousarray(mean, np.float32)
    if n_threads <= 0:
        n_threads = min(8, max(1, (os.cpu_count() or 2) - 1))
    rc = lib.ptpu_preprocess_u8_nhwc_to_f32_nchw(
        srcs, n, h, w, c,
        mean_c.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        inv_std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_float(scale),
        out.ctypes.data_as(ctypes.c_void_p), n_threads)
    if rc != 0:
        raise RuntimeError(f"ptpu_preprocess failed rc={rc}")
    return out
