// paddle_tpu native host runtime.
//
// TPU-native analogue of the reference's C++ data pipeline + host allocator
// (ref: paddle/fluid/operators/reader/blocking_queue.h,
//  paddle/fluid/memory/allocation/auto_growth_best_fit_allocator.cc,
//  paddle/fluid/framework/blocking_queue.h).
//
// Two pieces, exported with a plain C ABI for ctypes:
//
//  * Host memory pool — size-class auto-growth allocator for staging
//    buffers that sit between DataLoader workers and the device transfer.
//    Keeps allocation out of the per-batch hot path and reports the same
//    kind of statistics the reference's allocator facade exposes
//    (in-use / peak / reserved / allocation counts).
//
//  * Data ring — bounded MPMC blocking queue of staged batches.  Producers
//    (Python worker threads) gather a batch's arrays into ONE pool slab
//    with a single C-side memcpy pass (GIL released by ctypes), consumers
//    pop slabs FIFO and hand bytes to the device.  This is the overlap
//    mechanism: host collation/copy runs concurrently with the device step.
//
// Build: g++ -O3 -shared -fPIC -pthread (see build.py).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <new>
#include <unordered_map>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Host memory pool
// ---------------------------------------------------------------------------

inline uint64_t size_class(uint64_t n) {
  uint64_t c = 256;
  while (c < n) c <<= 1;
  return c;
}

struct Pool {
  std::mutex mu;
  std::map<uint64_t, std::vector<char*>> free_lists;  // size class -> blocks
  std::unordered_map<void*, uint64_t> block_class;    // live block -> class
  uint64_t reserved = 0;      // total bytes obtained from the OS
  uint64_t in_use = 0;        // bytes handed out (class-rounded)
  uint64_t peak_in_use = 0;
  uint64_t alloc_count = 0;   // pool_alloc calls
  uint64_t grow_count = 0;    // OS allocations (cache misses)
  uint64_t free_count = 0;

  ~Pool() {
    for (auto& kv : free_lists)
      for (char* p : kv.second) ::operator delete[](p, std::nothrow);
    for (auto& kv : block_class) ::operator delete[]((char*)kv.first,
                                                     std::nothrow);
  }

  void* alloc(uint64_t n) {
    uint64_t cls = size_class(n);
    std::lock_guard<std::mutex> g(mu);
    alloc_count++;
    char* p = nullptr;
    auto it = free_lists.find(cls);
    if (it != free_lists.end() && !it->second.empty()) {
      p = it->second.back();
      it->second.pop_back();
    } else {
      p = static_cast<char*>(::operator new[](cls, std::nothrow));
      if (p == nullptr) return nullptr;
      grow_count++;
      reserved += cls;
    }
    block_class[p] = cls;
    in_use += cls;
    if (in_use > peak_in_use) peak_in_use = in_use;
    return p;
  }

  void release(void* p) {
    std::lock_guard<std::mutex> g(mu);
    auto it = block_class.find(p);
    if (it == block_class.end()) return;  // double free / foreign pointer
    uint64_t cls = it->second;
    block_class.erase(it);
    in_use -= cls;
    free_count++;
    free_lists[cls].push_back(static_cast<char*>(p));
  }

  // Return every cached (free-list) block to the OS.  Blocks still
  // handed out are untouched — their release() later just re-caches
  // them.  Used by the graveyard path so a destroyed-but-unreclaimable
  // object pins only its shell, not its slabs.
  void trim() {
    std::lock_guard<std::mutex> g(mu);
    for (auto& kv : free_lists) {
      for (char* p : kv.second) {
        ::operator delete[](p, std::nothrow);
        reserved -= kv.first;
      }
      kv.second.clear();
    }
  }
};

// ---------------------------------------------------------------------------
// Data ring
// ---------------------------------------------------------------------------

struct Slab {
  void* data;
  uint64_t len;
  uint64_t tag;
};

struct Ring {
  explicit Ring(int capacity) : cap(capacity) {}
  Pool pool;
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
  std::deque<Slab> q;
  int cap;
  int inflight = 0;  // producers that reserved a slot and are copying
  bool closed = false;
  uint64_t pushed = 0;
  uint64_t popped = 0;

  // codes: 0 ok, -1 closed, -2 timeout, -3 oom
  int push_gather(const void* const* srcs, const uint64_t* lens, int n,
                  uint64_t tag, int timeout_ms) {
    uint64_t total = 0;
    for (int i = 0; i < n; i++) total += lens[i];
    if (total == 0) total = 1;
    std::unique_lock<std::mutex> lk(mu);
    auto has_room = [&] { return (int)q.size() + inflight < cap || closed; };
    if (timeout_ms < 0) {
      not_full.wait(lk, has_room);
    } else if (!not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                  has_room)) {
      return -2;
    }
    if (closed) return -1;
    inflight++;  // hard-bound the queue even while copying unlocked
    lk.unlock();
    // copy outside the lock: other producers/consumers keep moving
    char* slab = static_cast<char*>(pool.alloc(total));
    if (slab == nullptr) {
      lk.lock();
      inflight--;
      lk.unlock();
      // the freed reservation may be the room another producer waits
      // for: without this wake it can sleep forever (missed wakeup)
      not_full.notify_one();
      return -3;
    }
    uint64_t off = 0;
    for (int i = 0; i < n; i++) {
      std::memcpy(slab + off, srcs[i], lens[i]);
      off += lens[i];
    }
    lk.lock();
    inflight--;
    if (closed) {  // closed while copying
      lk.unlock();
      pool.release(slab);
      not_full.notify_one();
      return -1;
    }
    q.push_back(Slab{slab, total, tag});
    pushed++;
    lk.unlock();
    not_empty.notify_one();
    return 0;
  }

  int pop(void** out_ptr, uint64_t* out_len, uint64_t* out_tag,
          int timeout_ms) {
    std::unique_lock<std::mutex> lk(mu);
    auto ready = [&] { return !q.empty() || closed; };
    if (timeout_ms < 0) {
      not_empty.wait(lk, ready);
    } else if (!not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   ready)) {
      return -2;
    }
    if (q.empty()) return -1;  // closed and drained
    Slab s = q.front();
    q.pop_front();
    popped++;
    lk.unlock();
    not_full.notify_one();
    *out_ptr = s.data;
    *out_len = s.len;
    *out_tag = s.tag;
    return 0;
  }

  void close() {
    {
      std::lock_guard<std::mutex> g(mu);
      closed = true;
    }
    not_full.notify_all();
    not_empty.notify_all();
  }
};

std::mutex g_mu;
std::unordered_map<int64_t, Pool*> g_pools;
std::unordered_map<int64_t, Ring*> g_rings;
std::atomic<int64_t> g_next{1};

Pool* get_pool(int64_t h) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_pools.find(h);
  return it == g_pools.end() ? nullptr : it->second;
}

Ring* get_ring(int64_t h) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_rings.find(h);
  return it == g_rings.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

// ---- pool ----
int64_t ptpu_pool_create() {
  int64_t h = g_next++;
  std::lock_guard<std::mutex> g(g_mu);
  g_pools[h] = new Pool();
  return h;
}

std::vector<Pool*>& pool_graveyard() {
  static std::vector<Pool*> g;
  return g;
}

void ptpu_pool_destroy(int64_t h) {
  Pool* p;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_pools.find(h);
    if (it == g_pools.end()) return;
    p = it->second;
    g_pools.erase(it);
    pool_graveyard().push_back(p);  // see ring_graveyard rationale
  }
  p->trim();  // cached blocks back to the OS; only the shell is pinned
}

void* ptpu_pool_alloc(int64_t h, uint64_t n) {
  Pool* p = get_pool(h);
  return p ? p->alloc(n) : nullptr;
}

void ptpu_pool_free(int64_t h, void* ptr) {
  Pool* p = get_pool(h);
  if (p) p->release(ptr);
}

// out[0..6] = reserved, in_use, peak_in_use, alloc_count, grow_count,
//             free_count
void ptpu_pool_stats(int64_t h, uint64_t* out) {
  Pool* p = get_pool(h);
  if (!p) { std::memset(out, 0, 6 * sizeof(uint64_t)); return; }
  std::lock_guard<std::mutex> g(p->mu);
  out[0] = p->reserved;
  out[1] = p->in_use;
  out[2] = p->peak_in_use;
  out[3] = p->alloc_count;
  out[4] = p->grow_count;
  out[5] = p->free_count;
}

// ---- ring ----
int64_t ptpu_ring_create(int capacity) {
  if (capacity <= 0) capacity = 2;
  int64_t h = g_next++;
  std::lock_guard<std::mutex> g(g_mu);
  g_rings[h] = new Ring(capacity);
  return h;
}

// Destroyed objects go to a graveyard instead of delete: another thread
// may still hold a raw pointer from get_ring()/get_pool() or be blocked
// on the ring's condvars — deleting under it is a use-after-free.  close()
// wakes every waiter and all later ops fail cleanly via the erased handle;
// the object itself (a few hundred bytes + its pool, whose slabs ARE
// freed by close/release) lives until process exit.  Rings are created
// per-DataLoader-epoch at most — the leak is bounded and tiny.
std::vector<Ring*>& ring_graveyard() {
  static std::vector<Ring*> g;
  return g;
}

void ptpu_ring_destroy(int64_t h) {
  Ring* r;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_rings.find(h);
    if (it == g_rings.end()) return;
    r = it->second;
    g_rings.erase(it);
  }
  r->close();
  // reclaim the actual memory: drain queued slabs into the pool's free
  // lists, then trim those to the OS.  A racing producer mid-copy still
  // holds its own slab; its release() lands in the (trimmed-later-never)
  // free list — bytes bounded by in-flight batches at destroy time.
  {
    std::lock_guard<std::mutex> lk(r->mu);
    for (auto& s : r->q) r->pool.release(s.data);
    r->q.clear();
  }
  r->pool.trim();
  {
    std::lock_guard<std::mutex> g(g_mu);
    ring_graveyard().push_back(r);
  }
}

int ptpu_ring_push_gather(int64_t h, const void* const* srcs,
                          const uint64_t* lens, int n, uint64_t tag,
                          int timeout_ms) {
  Ring* r = get_ring(h);
  return r ? r->push_gather(srcs, lens, n, tag, timeout_ms) : -1;
}

int ptpu_ring_pop(int64_t h, void** out_ptr, uint64_t* out_len,
                  uint64_t* out_tag, int timeout_ms) {
  Ring* r = get_ring(h);
  return r ? r->pop(out_ptr, out_len, out_tag, timeout_ms) : -1;
}

void ptpu_ring_release(int64_t h, void* ptr) {
  Ring* r = get_ring(h);
  if (r) r->pool.release(ptr);
}

void ptpu_ring_close(int64_t h) {
  Ring* r = get_ring(h);
  if (r) r->close();
}

int ptpu_ring_size(int64_t h) {
  Ring* r = get_ring(h);
  if (!r) return -1;
  std::lock_guard<std::mutex> g(r->mu);
  return (int)r->q.size();
}

void ptpu_ring_stats(int64_t h, uint64_t* out) {
  Ring* r = get_ring(h);
  if (!r) { std::memset(out, 0, 8 * sizeof(uint64_t)); return; }
  std::lock_guard<std::mutex> g(r->mu);
  out[0] = r->pushed;
  out[1] = r->popped;
  {
    std::lock_guard<std::mutex> pg(r->pool.mu);
    out[2] = r->pool.reserved;
    out[3] = r->pool.in_use;
    out[4] = r->pool.peak_in_use;
    out[5] = r->pool.alloc_count;
    out[6] = r->pool.grow_count;
    out[7] = r->pool.free_count;
  }
}

// ---- fused image preprocess ----
//
// The reference's vision data path does uint8 decode -> float normalize ->
// HWC->CHW transpose per image in Python workers (ref:
// python/paddle/vision/transforms/functional.py::normalize/to_tensor);
// this fuses all three into one threaded C pass so DataLoader collation
// feeds the host->HBM staging ring at memory bandwidth.
//
// srcs: n pointers to u8 [H, W, C] images; out: f32 [n, C, H, W];
// out[i][ch][y][x] = (src[y][x][ch] * scale - mean[ch]) * inv_std[ch].
int ptpu_preprocess_u8_nhwc_to_f32_nchw(const uint8_t* const* srcs, int n,
                                        int h, int w, int c,
                                        const float* mean,
                                        const float* inv_std, float scale,
                                        float* out, int n_threads) {
  if (n <= 0 || h <= 0 || w <= 0 || c <= 0 || c > 16) return -1;
  const int64_t plane = static_cast<int64_t>(h) * w;
  const int64_t img_out = plane * c;
  float pre_mul[16], pre_sub[16];
  for (int ch = 0; ch < c; ++ch) {
    pre_mul[ch] = scale * inv_std[ch];
    pre_sub[ch] = mean[ch] * inv_std[ch];
  }
  auto work = [&](int begin, int end) {
    for (int i = begin; i < end; ++i) {
      const uint8_t* src = srcs[i];
      float* dst = out + i * img_out;
      for (int64_t p = 0; p < plane; ++p) {
        const uint8_t* px = src + p * c;
        for (int ch = 0; ch < c; ++ch) {
          dst[ch * plane + p] = px[ch] * pre_mul[ch] - pre_sub[ch];
        }
      }
    }
  };
  int threads = n_threads > 0 ? n_threads : 1;
  if (threads > n) threads = n;
  if (threads <= 1) {
    work(0, n);
    return 0;
  }
  std::vector<std::thread> pool;
  pool.reserve(threads);
  const int chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    const int b = t * chunk;
    const int e = b + chunk < n ? b + chunk : n;
    if (b >= e) break;
    pool.emplace_back(work, b, e);
  }
  for (auto& th : pool) th.join();
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// WordPiece tokenizer (ref: the ERNIE/BERT data pipeline's host-side
// tokenization — reference tokenization.py implements the same algorithm
// in Python; models feed int ids).  Basic tokenize (whitespace +
// punctuation split, optional ASCII lowercase) then greedy longest-match
// wordpiece with a "##" continuation prefix.  UTF-8 bytes outside ASCII
// pass through opaquely (multi-byte chars are treated as atomic units).
namespace wp {

struct Tok {
  std::unordered_map<std::string, int> vocab;
  int unk_id = 0;
  std::string cont = "##";
};

std::unordered_map<int64_t, Tok*> g_toks;

inline bool is_punct(unsigned char c) {
  return (c >= 33 && c <= 47) || (c >= 58 && c <= 64) ||
         (c >= 91 && c <= 96) || (c >= 123 && c <= 126);
}

// one UTF-8 character's byte length from its lead byte
inline int u8len(unsigned char c) {
  if (c < 0x80) return 1;
  if ((c >> 5) == 0x6) return 2;
  if ((c >> 4) == 0xE) return 3;
  if ((c >> 3) == 0x1E) return 4;
  return 1;
}

void wordpiece(const Tok& tk, const std::string& word,
               std::vector<int>* out) {
  if (word.empty()) return;
  size_t start = 0;
  std::vector<int> pieces;
  while (start < word.size()) {
    size_t end = word.size();
    int found = -1;
    size_t found_end = start;
    while (end > start) {
      std::string sub = word.substr(start, end - start);
      if (start > 0) sub = tk.cont + sub;
      auto it = tk.vocab.find(sub);
      if (it != tk.vocab.end()) { found = it->second; found_end = end; break; }
      // shrink by one UTF-8 char from the right
      size_t e = start;
      size_t prev = start;
      while (e < end) { prev = e; e += u8len((unsigned char)word[e]); }
      end = prev;
    }
    if (found < 0) { out->push_back(tk.unk_id); return; }
    pieces.push_back(found);
    start = found_end;
  }
  out->insert(out->end(), pieces.begin(), pieces.end());
}

}  // namespace wp

extern "C" {

int64_t ptpu_wp_create(const char* vocab_data, int64_t len,
                       const char* unk_token) {
  auto* tk = new wp::Tok();
  // vocab: newline-separated tokens; line index = id
  int id = 0;
  const char* p = vocab_data;
  const char* endp = vocab_data + len;
  while (p < endp) {
    const char* nl = (const char*)memchr(p, '\n', endp - p);
    size_t n = nl ? (size_t)(nl - p) : (size_t)(endp - p);
    while (n > 0 && (p[n - 1] == '\r')) --n;
    // LAST duplicate wins, matching the Python dict load (reference
    // tokenization.py reads sequentially with plain assignment)
    if (n > 0) tk->vocab[std::string(p, n)] = id;
    ++id;
    if (!nl) break;
    p = nl + 1;
  }
  auto it = tk->vocab.find(unk_token ? unk_token : "[UNK]");
  tk->unk_id = it == tk->vocab.end() ? 0 : it->second;
  int64_t h = g_next++;
  std::lock_guard<std::mutex> g(g_mu);
  wp::g_toks[h] = tk;
  return h;
}

std::vector<wp::Tok*>& tok_graveyard() {
  static std::vector<wp::Tok*> g;
  return g;
}

void ptpu_wp_destroy(int64_t h) {
  wp::Tok* t;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = wp::g_toks.find(h);
    if (it == wp::g_toks.end()) return;
    t = it->second;
    wp::g_toks.erase(it);
    tok_graveyard().push_back(t);  // see ring_graveyard rationale
  }
}

int64_t ptpu_wp_encode(int64_t h, const char* text, int64_t text_len,
                       int do_lower, int* out_ids, int64_t max_out) {
  wp::Tok* tk;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = wp::g_toks.find(h);
    if (it == wp::g_toks.end()) return -1;
    tk = it->second;
  }
  std::vector<int> ids;
  std::string word;
  auto flush = [&]() {
    if (!word.empty()) { wp::wordpiece(*tk, word, &ids); word.clear(); }
  };
  int64_t i = 0;
  while (i < text_len) {
    unsigned char c = (unsigned char)text[i];
    if (c < 0x80) {
      // match Python str.isspace for ASCII: 9-13, 28-31, 32
      if (c == 32 || (c >= 9 && c <= 13) || (c >= 28 && c <= 31)) {
        flush(); ++i; continue;
      }
      if (wp::is_punct(c)) {
        flush();
        word.assign(1, (char)c);
        flush();
        ++i;
        continue;
      }
      word.push_back(do_lower ? (char)tolower(c) : (char)c);
      ++i;
    } else {
      int n = wp::u8len(c);
      for (int k = 0; k < n && i < text_len; ++k, ++i)
        word.push_back(text[i]);
    }
  }
  flush();
  int64_t n = (int64_t)ids.size() < max_out ? (int64_t)ids.size() : max_out;
  for (int64_t k = 0; k < n; ++k) out_ids[k] = ids[k];
  return (int64_t)ids.size();
}

}  // extern "C"

