"""Build the native runtime shared library.

Compiles ptpu_runtime.cc -> libptpu_runtime.so next to this file.  Invoked
lazily on first import of paddle_tpu.runtime (idempotent: skipped when the
.so is newer than the source) or directly: python -m paddle_tpu.runtime.build
"""
from __future__ import annotations

import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_HERE, "ptpu_runtime.cc")
LIB = os.path.join(_HERE, "libptpu_runtime.so")


def build(force=False, quiet=True):
    """Compile the runtime if needed; returns the .so path or None."""
    if (not force and os.path.exists(LIB)
            and os.path.getmtime(LIB) >= os.path.getmtime(SRC)):
        return LIB
    for cxx in (os.environ.get("CXX"), "g++", "c++", "clang++"):
        if not cxx:
            continue
        cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
               SRC, "-o", LIB + ".tmp"]
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=300)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if res.returncode == 0:
            os.replace(LIB + ".tmp", LIB)
            return LIB
        if not quiet:
            sys.stderr.write(res.stderr)
    return None


if __name__ == "__main__":
    path = build(force="--force" in sys.argv, quiet=False)
    if path is None:
        sys.exit("native runtime build failed")
    print(path)
