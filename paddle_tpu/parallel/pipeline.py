"""Pipeline parallelism: GPipe-style microbatching over the 'pp' mesh axis.

Replaces the reference's section-based pipeline trainer
(ref: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py +
fluid device_worker SectionWorker): each pp rank holds a stack of layer
parameters; activations flow stage-to-stage with ppermute inside a
shard_map, microbatches keep every stage busy after warmup.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..framework.jax_compat import axis_size as _axis_size
from ..framework.jax_compat import partition_spec as P
from ..framework.jax_compat import shard_map


def pipeline_forward(stage_fn, x_global, n_microbatch, axis_name="pp"):
    """Run inside shard_map over ``axis_name``.

    stage_fn(x) -> y  applies THIS stage's chunk of layers (close over the
    stage's parameters; the leading stage axis is already split by shard_map).
    x_global: [B, ...] microbatchable input (replicated across pp).
    Returns final-stage output broadcast to all stages ([B, ...]).
    """
    idx = jax.lax.axis_index(axis_name)
    size = _axis_size(axis_name)
    B = x_global.shape[0]
    if B % n_microbatch:
        raise ValueError(
            f"batch {B} must divide by n_microbatch {n_microbatch}")
    mb = B // n_microbatch
    micro = x_global.reshape(n_microbatch, mb, *x_global.shape[1:])

    n_ticks = n_microbatch + size - 1
    state = jnp.zeros_like(micro[0])          # activation currently held
    outputs = jnp.zeros_like(micro)

    def tick(t, carry):
        state, outputs = carry
        # stage 0 ingests microbatch t (if any remain)
        feed = micro[jnp.minimum(t, n_microbatch - 1)]
        state = jnp.where(idx == 0,
                          jnp.where(t < n_microbatch, feed, state), state)
        out = stage_fn(state)
        # last stage writes its finished microbatch
        done_idx = t - (size - 1)
        write = (idx == size - 1) & (done_idx >= 0)
        outputs = jax.lax.cond(
            write,
            lambda o: o.at[jnp.maximum(done_idx, 0)].set(out),
            lambda o: o, outputs)
        # shift activations to the next stage
        perm = [(j, (j + 1) % size) for j in range(size)]
        state = jax.lax.ppermute(out, axis_name, perm)
        return state, outputs

    state, outputs = jax.lax.fori_loop(0, n_ticks, tick, (state, outputs))
    # broadcast final outputs (resident on last stage) to every stage:
    # mask+psum, since ppermute is one-to-one and can't fan out
    outputs = jax.lax.psum(
        jnp.where(idx == size - 1, outputs, jnp.zeros_like(outputs)),
        axis_name) if size > 1 else outputs
    return outputs.reshape(B, *outputs.shape[2:])


def make_pipelined(mesh, stage_fn, n_stages, n_microbatch, axis_name="pp"):
    """Build a pjit-able pipelined forward over GLOBAL stacked params.

    stage_fn(stage_params, x) -> y ; stage_params has leading axis
    ``layers_per_stage`` (scanned inside the stage).
    Global params have leading axis n_stages*layers_per_stage, sharded over
    ``axis_name``.
    """
    def run(params_stacked, x):
        def body(p_local, xg):
            f = functools.partial(stage_fn, p_local)
            return pipeline_forward(f, xg, n_microbatch, axis_name)
        return shard_map(
            body, mesh=mesh,
            in_specs=(P(axis_name), P()),
            out_specs=P(),
        )(params_stacked, x)
    return run
