"""Expert parallelism: Mixture-of-Experts FFN over a mesh axis.

TPU-native expert-parallel layer (the reference line grows this as
incubate/distributed/models/moe with NCCL alltoall; here the whole MoE
block is SPMD inside ``shard_map``):

  * switch-style top-1 routing with capacity buffers (static shapes —
    XLA needs a fixed [E, C, H] dispatch tensor; overflow tokens fall
    through with their residual, the standard Switch-Transformer drop);
  * experts are SHARDED over the mesh axis (``ep``, commonly reusing the
    dp axis): each rank holds E/size experts, tokens travel to their
    expert's rank via ``lax.all_to_all`` riding ICI and come back the
    same way;
  * everything is differentiable: routing probabilities scale the
    combined output (straight-through over the hard top-1 choice), and
    the auxiliary load-balancing loss is returned alongside.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ..framework.jax_compat import axis_size as _axis_size


def init_moe_params(key, n_experts, hidden, ffn, dtype=jnp.float32):
    """Gate + stacked expert FFN weights ([E, ...] leading expert axis —
    shard it over the ep axis with P('ep', ...)."""
    ks = jax.random.split(key, 3)
    std = 0.02
    return {
        "gate_w": jax.random.normal(ks[0], (hidden, n_experts),
                                    jnp.float32).astype(dtype) * std,
        "w1": jax.random.normal(ks[1], (n_experts, hidden, ffn),
                                jnp.float32).astype(dtype) * std,
        "b1": jnp.zeros((n_experts, ffn), dtype),
        "w2": jax.random.normal(ks[2], (n_experts, ffn, hidden),
                                jnp.float32).astype(dtype) * std,
        "b2": jnp.zeros((n_experts, hidden), dtype),
    }


def sharding_rules(cfg=None, axis_name="tp"):
    """Model-parallel layout hook for the distributed.auto rule registry
    (family "moe"): the gate replicates, every expert-stacked leaf
    shards its leading [E] axis over ``axis_name`` — on the auto mesh
    experts ride the 'tp' axis (the classic ep-on-mp placement; pass
    ``axis_name="ep"`` for a dedicated expert axis)."""
    from ..framework.jax_compat import partition_spec as P
    return {
        "gate_w": P(),
        "w1": P(axis_name), "b1": P(axis_name),
        "w2": P(axis_name), "b2": P(axis_name),
    }


def moe_ffn(x, params, axis_name="ep", capacity_factor=1.25,
            n_experts=None):
    """x: LOCAL [T, H] tokens inside a shard_map over ``axis_name``;
    params: LOCAL shards — gate_w replicated [H, E], expert weights
    [E_local, ...] (expert axis sharded over ``axis_name``).

    Returns (out [T, H], aux_loss scalar)."""
    size = _axis_size(axis_name)
    T, H = x.shape
    e_local = params["w1"].shape[0]
    E = n_experts or e_local * size
    assert e_local * size == E, (e_local, size, E)
    C = max(1, int(math.ceil(T / E * capacity_factor)))

    xf = x.astype(jnp.float32)
    logits = xf @ params["gate_w"].astype(jnp.float32)       # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                       # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    # position of each token within its expert's capacity buffer
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)       # [T, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot                 # 1-based
    pos_in_e = jnp.sum(pos, axis=1) - 1                       # [T]
    keep = pos_in_e < C

    # scatter tokens into the [E, C, H] dispatch buffer (dropped -> zeros)
    disp = jnp.zeros((E, C, H), x.dtype)
    e_idx = jnp.where(keep, expert, 0)
    c_idx = jnp.clip(pos_in_e, 0, C - 1)
    disp = disp.at[e_idx, c_idx].add(
        jnp.where(keep[:, None], x, 0).astype(x.dtype))

    # tokens travel to their expert's rank: [E, C, H] -> regroup so this
    # rank holds its local experts' tokens from EVERY rank
    disp = disp.reshape(size, e_local, C, H)
    disp = jax.lax.all_to_all(disp, axis_name, split_axis=0,
                              concat_axis=0, tiled=False)
    # [size, e_local, C, H]: axis 0 = source rank
    disp = jnp.swapaxes(disp, 0, 1).reshape(e_local, size * C, H)

    # local expert FFN (batched einsum over the expert axis -> MXU)
    h = jnp.einsum("ech,ehf->ecf", disp.astype(jnp.float32),
                   params["w1"].astype(jnp.float32))
    h = jax.nn.gelu(h + params["b1"].astype(jnp.float32)[:, None, :],
                    approximate=True)
    y = jnp.einsum("ecf,efh->ech", h, params["w2"].astype(jnp.float32))
    y = y + params["b2"].astype(jnp.float32)[:, None, :]

    # return trip
    y = y.reshape(e_local, size, C, H).swapaxes(0, 1)        # [size,e_l,C,H]
    y = jax.lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                           tiled=False)
    y = y.reshape(E, C, H)

    # gather each surviving token's output, scale by its gate prob
    out = y[e_idx, c_idx]                                     # [T, H]
    out = jnp.where(keep[:, None], out * gate[:, None].astype(y.dtype),
                    0.0)

    # Switch load-balancing aux loss: E * sum_e f_e * P_e.  frac/mean_p are
    # shard-local statistics — pmean them so the returned scalar is truly
    # replicated (an unreduced value under a replicated out-spec would make
    # the backward psum inconsistent with the forward value).
    frac = jnp.mean(onehot.astype(jnp.float32), axis=0)       # [E]
    mean_p = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * mean_p)
    aux = jax.lax.pmean(aux, axis_name)
    return out.astype(x.dtype), aux


def moe_ffn_dense_reference(x, params_full, capacity_factor=None):
    """Single-device reference: every token through its argmax expert,
    no capacity limit (for parity tests; params_full has the FULL [E,...]
    expert axis)."""
    xf = x.astype(jnp.float32)
    logits = xf @ params_full["gate_w"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    w1 = params_full["w1"].astype(jnp.float32)[expert]       # [T, H, F]
    b1 = params_full["b1"].astype(jnp.float32)[expert]
    w2 = params_full["w2"].astype(jnp.float32)[expert]
    b2 = params_full["b2"].astype(jnp.float32)[expert]
    h = jax.nn.gelu(jnp.einsum("th,thf->tf", xf, w1) + b1,
                    approximate=True)
    y = jnp.einsum("tf,tfh->th", h, w2) + b2
    return (y * gate[:, None]).astype(x.dtype)
