"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context replacement for the reference's fused attention at scale: Q
stays resident per shard while K/V blocks rotate around the 'sp' ring via
ppermute, overlapping compute with ICI transfers.  Online-softmax running
stats merge partial results exactly (same math as flash attention).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, scale, causal, q_off, k_off):
    """Attention over one (q_shard, k_block) pair with running-stat outputs.
    q: [B,H,Nq,D]; returns (out_unnorm, row_max, row_sumexp)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        nq, nk = s.shape[-2], s.shape[-1]
        rows = q_off + jax.lax.broadcasted_iota(jnp.int32, (nq, nk), 0)
        cols = k_off + jax.lax.broadcasted_iota(jnp.int32, (nq, nk), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                 # [B,H,Nq,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o, m, l


def ring_attention(q, k, v, axis_name="sp", causal=False):
    """q,k,v: LOCAL shards [B, H, N_local, D] inside a shard_map over
    ``axis_name``.  Returns the local output shard."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    n_local = q.shape[2]
    idx = jax.lax.axis_index(axis_name)
    size = jax.lax.axis_size(axis_name)
    q_off = idx * n_local

    o, m, l = _block_attn(q, k, v, scale, causal, q_off, idx * n_local)

    def body(i, carry):
        o, m, l, k, v = carry
        # rotate K/V one step around the ring (overlaps with next compute)
        perm = [(j, (j + 1) % size) for j in range(size)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        src = (idx - i - 1) % size  # shard the K/V block originated from
        k_off = src * n_local
        o2, m2, l2 = _block_attn(q, k, v, scale, causal, q_off, k_off)
        m_new = jnp.maximum(m, m2)
        a1 = jnp.exp(m - m_new)
        a2 = jnp.exp(m2 - m_new)
        o = o * a1 + o2 * a2
        l = l * a1 + l2 * a2
        return o, m_new, l, k, v

    o, m, l, _, _ = jax.lax.fori_loop(0, size - 1, body, (o, m, l, k, v))
    return o / jnp.maximum(l, 1e-30)


def ring_attention_sharded(mesh, q, k, v, causal=False, axis_name="sp"):
    """Entry point on GLOBAL arrays [B,H,N,D]: shard N over ``axis_name``."""
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, axis_name, None),) * 3,
        out_specs=P(None, None, axis_name, None))
    return fn(q, k, v)
