"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context replacement for the reference's fused attention at scale: Q
stays resident per shard while K/V blocks rotate around the 'sp' ring via
ppermute, overlapping compute with ICI transfers.  Online-softmax running
stats merge partial results exactly (same math as flash attention).

The backward is a CUSTOM VJP (ring-flash): probabilities are never saved —
each step recomputes its score block from the saved per-row logsumexp
while dK/dV accumulators ride the rotating K/V around the full ring and
land home after `size` hops.  Without this, autodiff of the forward scan
would checkpoint a [B,H,Nq_local,Nk_local] probability block per ring
step (O(N^2/sp) per device) — exactly what kills long-context training.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ..framework.jax_compat import axis_size as _axis_size
from ..framework.jax_compat import partition_spec as P
from ..framework.jax_compat import shard_map

NEG_INF = -1e30


def _scores(q, k, scale, causal, q_off, k_off):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        nq, nk = s.shape[-2], s.shape[-1]
        rows = q_off + jax.lax.broadcasted_iota(jnp.int32, (nq, nk), 0)
        cols = k_off + jax.lax.broadcasted_iota(jnp.int32, (nq, nk), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    return s


def _block_attn(q, k, v, scale, causal, q_off, k_off):
    """Attention over one (q_shard, k_block) pair with running-stat outputs.
    q: [B,H,Nq,D]; returns (out_unnorm, row_max, row_sumexp) in fp32."""
    s = _scores(q, k, scale, causal, q_off, k_off)
    m = jnp.max(s, axis=-1, keepdims=True)                 # [B,H,Nq,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o, m, l


def _ring_fwd_impl(q, k, v, axis_name, causal):
    scale = 1.0 / math.sqrt(q.shape[-1])
    n_local = q.shape[2]
    idx = jax.lax.axis_index(axis_name)
    size = _axis_size(axis_name)
    q_off = idx * n_local

    o, m, l = _block_attn(q, k, v, scale, causal, q_off, idx * n_local)

    def body(i, carry):
        o, m, l, k, v = carry
        perm = [(j, (j + 1) % size) for j in range(size)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        src = (idx - i - 1) % size  # shard this K/V block originated from
        o2, m2, l2 = _block_attn(q, k, v, scale, causal, q_off,
                                 src * n_local)
        m_new = jnp.maximum(m, m2)
        a1 = jnp.exp(m - m_new)
        a2 = jnp.exp(m2 - m_new)
        o = o * a1 + o2 * a2
        l = l * a1 + l2 * a2
        return o, m_new, l, k, v

    o, m, l, _, _ = jax.lax.fori_loop(0, size - 1, body, (o, m, l, k, v))
    l = jnp.maximum(l, 1e-30)
    out = (o / l).astype(q.dtype)
    lse = m + jnp.log(l)                                   # [B,H,Nq,1]
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_attention(q, k, v, axis_name="sp", causal=False):
    """q,k,v: LOCAL shards [B, H, N_local, D] inside a shard_map over
    ``axis_name``.  Returns the local output shard."""
    out, _ = _ring_fwd_impl(q, k, v, axis_name, causal)
    return out


def _ring_fwd(q, k, v, axis_name, causal):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, causal)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, res, g):
    """Ring-flash backward.  dQ accumulates locally; (dK, dV) accumulators
    travel WITH the rotating K/V so after the full `size` hops they land
    back on the shard that owns those K/V rows."""
    q, k, v, out, lse = res
    scale = 1.0 / math.sqrt(q.shape[-1])
    n_local = q.shape[2]
    idx = jax.lax.axis_index(axis_name)
    size = _axis_size(axis_name)
    q_off = idx * n_local

    gf = g.astype(jnp.float32)
    of = out.astype(jnp.float32)
    qf = q.astype(jnp.float32)
    # delta_i = rowsum(dO_i * O_i)  [B,H,Nq,1]
    delta = jnp.sum(gf * of, axis=-1, keepdims=True)

    perm = [(j, (j + 1) % size) for j in range(size)]

    def compute(dq, dk_acc, dv_acc, k_rot, v_rot, i):
        src = (idx - i) % size           # owner of the current K/V block
        s = _scores(q, k_rot, scale, causal, q_off, src * n_local)
        p = jnp.exp(s - lse)             # recomputed, never stored
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf,
                        v_rot.astype(jnp.float32))
        ds = p * (dp - delta) * scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds,
                             k_rot.astype(jnp.float32))
        dk_acc = dk_acc + jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        dv_acc = dv_acc + jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        return dq, dk_acc, dv_acc

    def step(carry, i):
        dq, k_rot, v_rot, dk_acc, dv_acc = carry
        dq, dk_acc, dv_acc = compute(dq, dk_acc, dv_acc, k_rot, v_rot, i)
        # rotate K/V together with their gradient accumulators
        k_rot = jax.lax.ppermute(k_rot, axis_name, perm)
        v_rot = jax.lax.ppermute(v_rot, axis_name, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
        return (dq, k_rot, v_rot, dk_acc, dv_acc), None

    # accumulators must carry the same varying-manual-axes type as the
    # rotating k/v (shard_map VMA tracking) — derive them from the inputs
    zeros_k = k.astype(jnp.float32) * 0.0
    zeros_v = v.astype(jnp.float32) * 0.0
    init = (qf * 0.0, k, v, zeros_k, zeros_v)
    (dq, k_rot, v_rot, dk, dv), _ = jax.lax.scan(
        step, init, jnp.arange(size - 1))
    # last block: compute, then rotate ONLY the accumulators home — the
    # k/v blocks themselves have no further consumer (dead ICI otherwise)
    dq, dk, dv = compute(dq, dk, dv, k_rot, v_rot, size - 1)
    dk = jax.lax.ppermute(dk, axis_name, perm)
    dv = jax.lax.ppermute(dv, axis_name, perm)
    # after `size` rotations the accumulators are home: each shard now
    # holds the gradient of ITS OWN k/v rows summed over every q shard
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ring_attention_sharded(mesh, q, k, v, causal=False, axis_name="sp"):
    """Entry point on GLOBAL arrays [B,H,N,D]: shard N over ``axis_name``."""
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name,
                          causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, axis_name, None),) * 3,
        out_specs=P(None, None, axis_name, None))
    return fn(q, k, v)
