"""ZeRO optimizer-state / gradient / parameter sharding — the compiled path.

TPU-native re-design of the reference's fleet sharding meta-optimizer
(ref: python/paddle/distributed/fleet/meta_optimizers/sharding_optimizer.py,
sharding/offload_helper.py).  The reference rewrites the static program to
insert c_reduce_scatter/c_allgather ops around the optimizer block; here the
WHOLE train step — forward, backward, grad reduction, sharded AdamW update,
parameter regathering — is one ``shard_map`` program over the 'dp' mesh
axis, and the stage picks which collectives appear:

  stage 1  grads all-reduced (``psum``) full; AdamW runs on each rank's
           1/dp shard of the moments; updated param shards all-gathered.
  stage 2  grads ``psum_scatter`` (reduce-scatter) — each rank only ever
           holds its 1/dp grad shard; otherwise as stage 1.
  stage 3  parameters THEMSELVES live sharded; they are all-gathered
           just-in-time at the top of the step (gather-on-use FSDP),
           grads reduce-scattered, updates applied shard-local, and the
           step returns still-sharded parameters.

Sub-axis sharding: every leaf is flattened to 1-D and padded to a multiple
of dp, so tensors WITHOUT a dp-divisible axis shard too — no silent
replication (the round-2 verdict's complaint about the eager heuristic).
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from ..framework.jax_compat import shard_map, psum_scatter
from ..framework.jax_compat import named_sharding, partition_spec as P

from ..optimizer.functional import adamw_update


# --------------------------------------------------------------------------
# flat 1-D sharded representation
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _SD:
    """Shape+dtype leaf marker (unambiguous under tree_map)."""
    shape: tuple
    dtype: object


def _shapes_of(tree):
    return jax.tree_util.tree_map(
        lambda x: _SD(tuple(x.shape), x.dtype), tree)


def _pad_len(n, dp):
    return (n + dp - 1) // dp * dp


def flatten_leaf(x, dp):
    """[...] -> [dp, ceil(n/dp)] padded flat view."""
    flat = x.reshape(-1)
    padded = _pad_len(flat.size, dp)
    if padded != flat.size:
        flat = jnp.pad(flat, (0, padded - flat.size))
    return flat.reshape(dp, padded // dp)


def unflatten_leaf(flat2d, shape, dtype=None):
    n = math.prod(shape) if shape else 1
    out = flat2d.reshape(-1)[:n].reshape(shape)
    return out.astype(dtype) if dtype is not None else out


def shard_tree(tree, mesh, dp_axis="dp"):
    """Pytree of arrays -> pytree of [dp, k] leaves placed sharded on dp."""
    dp = dict(zip(mesh.axis_names, mesh.devices.shape))[dp_axis]
    ns = named_sharding(mesh, P(dp_axis))

    def go(x):
        return jax.device_put(flatten_leaf(x, dp), ns)
    return jax.tree_util.tree_map(go, tree)


def state_bytes_per_device(tree):
    """Bytes of the addressable shard of every leaf (ZeRO memory proof)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            total += shards[0].data.size * shards[0].data.dtype.itemsize
        else:
            total += leaf.size * leaf.dtype.itemsize
    return total


# --------------------------------------------------------------------------
# the compiled ZeRO train step
# --------------------------------------------------------------------------

def make_zero_train_step(loss_fn, param_template, mesh, stage=2,
                         lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
                         weight_decay=0.0, dp_axis="dp"):
    """Build ``step(opt_state, batch[, lr]) -> (opt_state, loss)``.

    loss_fn(params, batch) -> scalar loss (pure; params shaped like
    ``param_template``; batch leaves carry a leading batch dim sharded
    over dp).  opt_state comes from ``init_zero_state``.
    """
    assert stage in (1, 2, 3)
    dp = dict(zip(mesh.axis_names, mesh.devices.shape))[dp_axis]
    shapes = _shapes_of(param_template)
    is_sd = lambda x: isinstance(x, _SD)   # noqa: E731

    def local_step(params, m, v, t, batch, lr_t):
        if stage == 3:
            # gather-on-use: flat [1,k] local shard -> full tensors
            params = jax.tree_util.tree_map(
                lambda sd, fp: unflatten_leaf(
                    jax.lax.all_gather(fp, dp_axis, axis=0, tiled=True),
                    sd.shape, sd.dtype),
                shapes, params, is_leaf=is_sd)

        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        loss = jax.lax.pmean(loss, dp_axis)

        def reduce_grad(g):
            gf = flatten_leaf(g.astype(jnp.float32), dp)   # [dp, k]
            if stage >= 2:
                # reduce-scatter: rank i keeps row i summed — a full grad
                # tensor never exists on any rank
                return psum_scatter(
                    gf, dp_axis, scatter_dimension=0, tiled=False) / dp
            return (jax.lax.psum(gf, dp_axis) / dp)[
                jax.lax.axis_index(dp_axis)]

        gshard = jax.tree_util.tree_map(reduce_grad, grads)
        tf = t.astype(jnp.float32)

        def upd(sd, p, gs, mm, vv):
            # take THIS rank's flat param shard, update it shard-local
            pf = flatten_leaf(p.astype(jnp.float32), dp)[
                jax.lax.axis_index(dp_axis)]
            return adamw_update(pf, gs, mm[0], vv[0], lr_t, tf, beta1,
                                beta2, eps, weight_decay,
                                weight_decay > 0)

        out = jax.tree_util.tree_map(upd, shapes, params, gshard, m, v,
                                     is_leaf=is_sd)
        tup = lambda o: isinstance(o, tuple) and len(o) == 3  # noqa: E731
        new_ps = jax.tree_util.tree_map(lambda o: o[0], out, is_leaf=tup)
        new_m = jax.tree_util.tree_map(lambda o: o[1][None, :], out,
                                       is_leaf=tup)
        new_v = jax.tree_util.tree_map(lambda o: o[2][None, :], out,
                                       is_leaf=tup)

        if stage == 3:
            # params stay sharded: local [1,k] rows of the flat layout
            new_params = jax.tree_util.tree_map(
                lambda ps: ps[None, :], new_ps)
        else:
            # all-gather updated shards back into full replicated tensors
            new_params = jax.tree_util.tree_map(
                lambda sd, ps: unflatten_leaf(
                    jax.lax.all_gather(ps, dp_axis, axis=0),
                    sd.shape, sd.dtype),
                shapes, new_ps, is_leaf=is_sd)
        return new_params, new_m, new_v, loss

    pspec = jax.tree_util.tree_map(
        lambda _: P(dp_axis) if stage == 3 else P(), param_template)
    mspec = jax.tree_util.tree_map(lambda _: P(dp_axis), param_template)

    sharded = shard_map(local_step, mesh=mesh,
                        in_specs=(pspec, mspec, mspec, P(), P(dp_axis),
                                  P()),
                        out_specs=(pspec, mspec, mspec, P()),
                        check_vma=False)
    # no donation: init_zero_state's device_put can alias caller arrays
    # (same-sharding put is a no-op), and donating aliased buffers deletes
    # the caller's copies
    jitted = jax.jit(sharded)

    def step(opt_state, batch, lr_t=None):
        params, m, v, t = opt_state
        lr_val = jnp.float32(lr if lr_t is None else lr_t)
        new_params, new_m, new_v, loss = jitted(params, m, v, t, batch,
                                                lr_val)
        return (new_params, new_m, new_v, t + 1), loss

    return step


def init_zero_state(params, mesh, stage=2, dp_axis="dp"):
    """(params, m, v, t) with stage-appropriate placement."""
    m = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    v = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    m = shard_tree(m, mesh, dp_axis)
    v = shard_tree(v, mesh, dp_axis)
    if stage == 3:
        params = shard_tree(params, mesh, dp_axis)
    else:
        rep = named_sharding(mesh, P())
        params = jax.tree_util.tree_map(
            lambda p: jax.device_put(p, rep), params)
    return (params, m, v, jnp.int32(1))


def gather_params(opt_state, param_template, mesh, stage, dp_axis="dp"):
    """Recover full (unsharded) parameter tensors from a ZeRO state —
    for checkpointing / eval."""
    params = opt_state[0]
    if stage != 3:
        return params
    shapes = _shapes_of(param_template)
    return jax.tree_util.tree_map(
        lambda sd, fp: unflatten_leaf(jnp.asarray(fp), sd.shape, sd.dtype),
        shapes, params, is_leaf=lambda x: isinstance(x, _SD))
