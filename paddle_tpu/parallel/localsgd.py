"""LocalSGD — periodic parameter averaging instead of per-step grad sync.

ref: python/paddle/distributed/fleet/meta_optimizers/localsgd_optimizer.py
(LocalSGDOptimizer: snapshot params, run k local steps, allreduce the
param delta and average).  The reference exists to amortize slow GPU
interconnects; on a pod ICI makes per-step psum cheap, but LocalSGD is
still meaningful across DCN-connected slices or at very large dp degrees,
so it ships as a real capability rather than a warn-stub (VERDICT r4
item 7).

Two forms, matching the framework's two execution styles:

- ``localsgd_param_sync``: the SPMD primitive — call inside a
  shard_map'd train step whose params carry a PER-RANK copy (no grad
  psum); every ``k_steps`` it pmean-averages the params over the dp axis
  under ``lax.cond`` (compiler-friendly: one fused collective, no host
  round-trip).
- ``LocalSGDOptimizer``: the fleet meta-optimizer wrapper spelling —
  wraps any eager optimizer; each ``step()`` runs the inner update, and
  on the k-step boundary averages parameters through the collective API
  (identity in a world of one, psum over the mapped axis inside a
  parallel region).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def localsgd_param_sync(params, step, k_steps, begin_step=1,
                        axis_name="dp"):
    """Average ``params`` over ``axis_name`` on every k-step boundary.

    ``step`` is a traced int32 (1-based).  Boundaries are
    ``step >= begin_step and (step - begin_step) % k_steps == 0`` — the
    modular form of the reference's ``step - last_step == k_steps``
    counter (equivalent cadence without a carried last_step var).
    Off-boundary steps return params unchanged; under jit the cond
    compiles to one fused branch, so non-sync steps pay zero collective
    cost.
    """
    step = jnp.asarray(step, jnp.int32)
    do = jnp.logical_and(step >= begin_step,
                         (step - begin_step) % jnp.int32(k_steps) == 0)

    def avg(ps):
        # pmean yields an axis-invariant value; pcast back to 'varying'
        # so both cond branches carry the same shard_map type
        from ..framework.jax_compat import pcast_varying
        return jax.tree_util.tree_map(
            lambda x: pcast_varying(lax.pmean(x, axis_name), axis_name),
            ps)

    return lax.cond(do, avg, lambda ps: ps, params)


class LocalSGDOptimizer:
    """Fleet meta-optimizer spelling (ref localsgd_optimizer.py:25).

    ``step()`` = inner step + parameter averaging on each boundary.  The
    averaging rides ``collective.all_reduce(AVG)``: inside a mapped
    parallel region it is a pmean over the dp axis; in a world of one it
    is the identity, so the wrapper is safe in every mode.
    """

    def __init__(self, inner_optimizer, k_steps=1, begin_step=1):
        self._inner = inner_optimizer
        self._k = max(int(k_steps), 1)
        self._begin = int(begin_step)
        self._t = 0

    @property
    def _parameters(self):
        return self._inner._parameters

    def __getattr__(self, name):
        # full optimizer surface (set_lr, _learning_rate, flags set on the
        # inner optimizer before wrapping, ...) delegates to the inner —
        # same contract as GradientMergeOptimizer
        if name == "_inner":         # pre-__init__ lookups must not recurse
            raise AttributeError(name)
        return getattr(self._inner, name)

    def step(self):
        self._inner.step()
        self._t += 1
        if self._t >= self._begin and (self._t - self._begin) % self._k == 0:
            self._sync_params()

    def _sync_params(self):
        import numpy as np
        from ..distributed import collective
        params = [p for p in self._inner._parameters if p is not None]
        if not params:
            return
        if (collective._current_axis(None) is None
                and collective._process_count() > 1):
            # one flat cross-process gather for the whole parameter tree —
            # a per-param all_reduce would pay one global barrier per
            # parameter, defeating the point of syncing every k steps
            flat = np.concatenate([
                np.asarray(p.numpy(), np.float32).ravel() for p in params])
            mean = collective._eager_rows(flat).mean(0)
            off = 0
            for p in params:
                n = int(np.prod(p.shape)) if p.shape else 1
                collective._adopt(p, mean[off:off + n].reshape(p.shape)
                                  .astype(np.asarray(p.numpy()).dtype))
                off += n
        else:
            for p in params:
                collective.all_reduce(p, op=collective.ReduceOp.AVG)

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)

    def get_lr(self):
        return self._inner.get_lr()

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, d):
        return self._inner.set_state_dict(d)

    def minimize(self, loss, **kwargs):
        from ..static.graph import in_static_mode
        if in_static_mode():
            # the Executor owns the static step loop, so this wrapper has
            # no per-step hook there — never a silent no-op: tell the
            # user where LocalSGD lives on the static/SPMD path
            import warnings
            warnings.warn(
                "LocalSGDOptimizer has no effect on the static Executor "
                "loop (it would average once at build time); use "
                "paddle_tpu.parallel.localsgd_param_sync inside the "
                "shard_map/pjit train step instead", UserWarning,
                stacklevel=2)
            return self._inner.minimize(loss, **kwargs)
        out = self._inner.minimize(loss, **kwargs)
        self._t += 1
        if self._t >= self._begin and (self._t - self._begin) % self._k == 0:
            self._sync_params()
        return out
