"""Device mesh management — the spine of all parallelism.

Replaces the reference's ring-id/communicator plumbing
(ref: paddle/fluid/platform/collective_helper.cc): one global
jax.sharding.Mesh with named axes ('dp','pp','tp','sp'); layers annotate
PartitionSpecs and XLA GSPMD inserts the ICI collectives.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax
from ..framework.jax_compat import (make_mesh, named_sharding,
                                    partition_spec_class)

_current_mesh = [None]

P = partition_spec_class()


def create_mesh(dp=1, tp=1, pp=1, sp=1, devices=None):
    devices = devices if devices is not None else jax.devices()
    n = dp * tp * pp * sp
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(dp, pp, tp, sp)
    mesh = make_mesh(arr, ("dp", "pp", "tp", "sp"))
    return mesh


def set_mesh(mesh):
    _current_mesh[0] = mesh
    return mesh


def get_mesh():
    return _current_mesh[0]


@contextlib.contextmanager
def mesh_scope(mesh):
    prev = _current_mesh[0]
    _current_mesh[0] = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _current_mesh[0] = prev


def sharding(*spec):
    mesh = get_mesh()
    if mesh is None:
        return None
    return named_sharding(mesh, P(*spec))


def shard_constraint(x, *spec):
    """with_sharding_constraint when a mesh is active; identity otherwise.
    Accepts Tensor or raw array (used inside traced layer forwards)."""
    from ..tensor.tensor import Tensor
    from ..ops.dispatch import call
    from ..framework import jax_compat
    mesh = get_mesh()
    if mesh is None:
        return x

    def _c(v):
        return jax_compat.with_sharding_constraint(v, mesh, P(*spec))
    if isinstance(x, Tensor):
        return call(_c, x, _name="sharding_constraint")
    return _c(x)


def shard_params(layer):
    """Materialize parameter shardings: device_put each param according to
    its _sharding_axes hint (set by meta-parallel layers)."""
    mesh = get_mesh()
    if mesh is None:
        return layer
    for _, p in layer.named_parameters():
        spec = getattr(p, "_sharding_axes", None) or ()
        ns = named_sharding(mesh, P(*spec))
        try:
            p.value = jax.device_put(p.value, ns)
        except ValueError:
            pass  # unshardable shape on this mesh: keep replicated
    return layer
