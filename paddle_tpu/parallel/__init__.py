"""TPU parallelism primitives: mesh management, ring attention, pipelining."""
from .mesh import (create_mesh, set_mesh, get_mesh, mesh_scope, sharding,
                   shard_constraint, shard_params, P)
from .ring_attention import ring_attention, ring_attention_sharded
from .pipeline import pipeline_forward, make_pipelined
from . import zero
from .zero import (make_zero_train_step, init_zero_state, gather_params,
                   state_bytes_per_device)
from . import moe
from .moe import moe_ffn, init_moe_params
from . import localsgd
from .localsgd import localsgd_param_sync, LocalSGDOptimizer
