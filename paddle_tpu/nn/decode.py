"""Decoding helpers: BeamSearchDecoder + dynamic_decode
(ref: python/paddle/nn/decode.py).

Eager greedy/beam loop; data-dependent termination runs on host (the
reference's while_op does the same through the executor).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..tensor.tensor import Tensor
from ..tensor import manipulation as manip
from ..tensor import creation
from . import functional as F


class Decoder:
    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        shape = x.shape
        expanded = manip.unsqueeze(x, [1])
        tiled = manip.tile(expanded, [1, beam_size] + [1] * (len(shape) - 1))
        return manip.reshape(tiled, [-1] + shape[1:])

    def _merge_batch_beams(self, x):
        return manip.reshape(x, [-1] + x.shape[2:])

    def _split_batch_beams(self, x):
        return manip.reshape(x, [-1, self.beam_size] + x.shape[1:])

    def initialize(self, initial_cell_states):
        states = initial_cell_states
        sample = states[0] if isinstance(states, (tuple, list)) else states
        batch = sample.shape[0]
        self.batch_size = batch
        self._parents = []      # per-step beam ancestry for gather_tree
        start = creation.full([batch, self.beam_size], self.start_token,
                              "int64")
        log_probs = creation.full([batch, self.beam_size], -1e9, "float32")
        log_probs = Tensor(log_probs.value.at[:, 0].set(0.0))
        finished = creation.zeros([batch, self.beam_size], "bool")

        def tile(s):
            return self.tile_beam_merge_with_batch(s, self.beam_size)
        if isinstance(states, (tuple, list)):
            states = tuple(tile(s) for s in states)
        else:
            states = tile(states)
        init_inputs = start
        return init_inputs, (states, log_probs, finished), finished

    def step(self, time, inputs, states, **kwargs):
        cell_states, log_probs, finished = states
        inp = inputs
        if self.embedding_fn is not None:
            inp = self.embedding_fn(inp)
        inp = self._merge_batch_beams(inp)
        cell_out, next_cell_states = self.cell(inp, cell_states)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        V = cell_out.shape[-1]
        logits = manip.reshape(cell_out, [-1, self.beam_size, V])
        step_lp = F.log_softmax(logits, axis=-1)

        lv = step_lp.value + log_probs.value[..., None]
        fin = finished.value
        # finished beams only extend with end_token at prob 0
        mask = jnp.full((V,), -1e9).at[self.end_token].set(0.0)
        lv = jnp.where(fin[..., None], log_probs.value[..., None] + mask, lv)
        flat = lv.reshape(self.batch_size, -1)
        import jax
        top_lp, top_idx = jax.lax.top_k(flat, self.beam_size)
        beam_idx = top_idx // V
        token_idx = top_idx % V
        new_finished = jnp.take_along_axis(fin, beam_idx, axis=1) | \
            (token_idx == self.end_token)

        def gather_state(s):
            sv = s.value if isinstance(s, Tensor) else s
            sv = sv.reshape(self.batch_size, self.beam_size, *sv.shape[1:])
            g = jnp.take_along_axis(
                sv, beam_idx.reshape(self.batch_size, self.beam_size,
                                     *([1] * (sv.ndim - 2))), axis=1)
            return Tensor(g.reshape(-1, *sv.shape[2:]))
        if isinstance(next_cell_states, (tuple, list)):
            next_cell_states = tuple(gather_state(s) for s in next_cell_states)
        else:
            next_cell_states = gather_state(next_cell_states)

        outputs = Tensor(token_idx.astype(jnp.int32))
        self._parents.append(Tensor(beam_idx.astype(jnp.int32)))
        next_states = (next_cell_states, Tensor(top_lp),
                       Tensor(new_finished))
        return outputs, next_states, outputs, Tensor(new_finished)

    def finalize(self, outputs, final_states, sequence_lengths):
        """outputs arrive TIME-MAJOR [T, B, beam]; beam slots at each step
        are post-prune and their ancestry hops beams, so the full paths
        are reconstructed with gather_tree over the recorded parent
        pointers (ref fluid gather_tree_op — the reference decoder does
        the same backtrace)."""
        if not self._parents:
            return outputs, final_states
        from .functional.extension import gather_tree
        parents = manip.stack(self._parents, axis=0)      # [T, B, beam]
        return gather_tree(outputs, parents), final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=100, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    inputs, states, finished = decoder.initialize(inits)
    outputs_list = []
    seq_len = None
    # per-sequence (and per-beam) length: a slot still counts the step
    # that first emits its end token, then freezes (reference
    # dynamic_decode tracks this via the finished mask)
    fin_np = np.asarray(finished.numpy()).astype(bool)
    lengths_np = np.zeros(fin_np.shape, np.int64)
    for t in range(int(max_step_num)):
        out, states, next_inputs, finished = decoder.step(t, inputs, states,
                                                          **kwargs)
        outputs_list.append(out)
        if decoder.tracks_own_finished and getattr(decoder, "_parents",
                                                   None):
            # beam slots were reordered by ancestry this step: slot j now
            # descends from old slot parents[j], so its running length
            # (and pre-step finished flag) must follow the reorder
            # (advisor r4: lengths previously tracked the slot position,
            # not the hypothesis)
            par = np.asarray(decoder._parents[-1].numpy()).astype(np.int64)
            lengths_np = np.take_along_axis(lengths_np, par, axis=-1)
            fin_np = np.take_along_axis(fin_np, par, axis=-1)
        lengths_np = lengths_np + (~fin_np).astype(np.int64)
        new_fin = np.asarray(finished.numpy()).astype(bool)
        # sticky finished (ref rnn.py:1509): once a row ends it stays
        # ended, unless the decoder manages its own mask (beam search
        # reorders slots, so its mask must be taken as-is)
        fin_np = new_fin if decoder.tracks_own_finished \
            else (fin_np | new_fin)
        inputs = next_inputs
        if bool(np.all(fin_np)):
            break
    # finalize always sees TIME-MAJOR [T, B, ...] (reference contract);
    # the requested orientation is applied after.  Step outputs may be a
    # structure (BasicDecoderOutput namedtuples) — stack leaf-wise.
    import jax.tree_util as jtu
    is_leaf = lambda x: isinstance(x, Tensor)     # noqa: E731
    outputs = jtu.tree_map(lambda *xs: manip.stack(list(xs), axis=0),
                           *outputs_list, is_leaf=is_leaf)
    outputs, final_states = decoder.finalize(outputs, states, seq_len)
    if not output_time_major:
        def _bm(x):
            perm = [1, 0] + list(range(2, len(x.shape)))
            return manip.transpose(x, perm)
        outputs = jtu.tree_map(_bm, outputs, is_leaf=is_leaf)
    if return_length:
        lengths = Tensor(lengths_np)
        return outputs, final_states, lengths
    return outputs, final_states
