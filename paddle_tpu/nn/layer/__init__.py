from .layers import Layer
from .container import Sequential, LayerList, ParameterList, LayerDict
from .common import (Identity, Linear, Flatten, Embedding, Dropout, Dropout2D,
                     Dropout3D, AlphaDropout, Upsample, UpsamplingNearest2D,
                     UpsamplingBilinear2D, Bilinear, CosineSimilarity,
                     PairwiseDistance, Pad1D, Pad2D, Pad3D, ZeroPad2D,
                     PixelShuffle, Unfold, Fold)
from .activation import (ReLU, ReLU6, Sigmoid, Tanh, Silu, Swish, Mish,
                         Hardswish, LogSigmoid, Softsign, Tanhshrink, GLU,
                         ELU, SELU, GELU, LeakyReLU, PReLU, RReLU, Hardshrink,
                         Hardsigmoid, Hardtanh, Softplus, Softshrink,
                         ThresholdedReLU, Maxout, Softmax, LogSoftmax)
from .conv import (Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose,
                   Conv3DTranspose)
from .norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                   SyncBatchNorm, LayerNorm, RMSNorm, GroupNorm,
                   InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
                   LocalResponseNorm, SpectralNorm)
from .pooling import (MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D,
                      AvgPool3D, AdaptiveAvgPool1D, AdaptiveAvgPool2D,
                      AdaptiveAvgPool3D, AdaptiveMaxPool1D, AdaptiveMaxPool2D,
                      AdaptiveMaxPool3D)
from .loss import (CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss,
                   BCEWithLogitsLoss, KLDivLoss, SmoothL1Loss,
                   MarginRankingLoss, CTCLoss, HSigmoidLoss,
                   TripletMarginLoss, CosineEmbeddingLoss,
                   HingeEmbeddingLoss)
from .rnn import (RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN,
                  SimpleRNN, LSTM, GRU)
from .transformer import (MultiHeadAttention, TransformerEncoderLayer,
                          TransformerEncoder, TransformerDecoderLayer,
                          TransformerDecoder, Transformer)
