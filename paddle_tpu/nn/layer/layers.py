"""Layer: the module base class (ref: python/paddle/fluid/dygraph/layers.py).

Parameters live as Tensors on the instance; the functionalization bridge in
jit/functional.py swaps their payloads for tracers so a whole Layer forward
(and train step) stages into one XLA computation — the reference instead
re-executes per-op kernels through its C++ tracer.
"""
from __future__ import annotations

import collections
import copy

import numpy as np

from ...framework import core
from ...tensor.tensor import Tensor, Parameter
from ..initializer import Constant, XavierUniform, Uniform, Initializer
from ...framework.param_attr import ParamAttr


class HookRemoveHelper:
    _next_id = [0]

    def __init__(self, hooks):
        self._hooks = hooks
        HookRemoveHelper._next_id[0] += 1
        self._id = HookRemoveHelper._next_id[0]

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        if name_scope is None:
            name_scope = self.__class__.__name__.lower()
        self._full_name = name_scope
        self._dtype = core.convert_dtype(dtype)
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names_set = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._casted_by_pure_fp16 = False

    # ------------------------------------------------------------- params
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        if attr is False:
            return None
        dtype = core.convert_dtype(dtype) or self._dtype or core.get_default_dtype()
        attr = ParamAttr._to_attr(attr)
        from ..initializer import _global_bias_init, _global_weight_init
        glob = _global_bias_init[0] if is_bias else _global_weight_init[0]
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif glob is not None:
            # set_global_initializer overrides the layer's own default
            # (reference layer_helper_base.create_parameter order)
            init = glob
        elif default_initializer is not None:
            init = default_initializer
        elif is_bias:
            init = Constant(0.0)
        else:
            init = XavierUniform()
        data = init(shape, dtype)
        p = Parameter(data, name=attr.name if attr and attr.name else None)
        if attr is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
            p.regularizer = attr.regularizer
            p.trainable = attr.trainable
            p.stop_gradient = not attr.trainable
            p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            if not isinstance(parameter, Parameter):
                parameter = Parameter(parameter)
            self._parameters[name] = parameter
        return parameter

    def create_variable(self, name=None, persistable=None, dtype=None):
        t = Tensor(np.zeros((), np.float32))
        t.persistable = bool(persistable)
        return t

    def create_tensor(self, name=None, persistable=None, dtype=None):
        return self.create_variable(name, persistable, dtype)

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        if tensor is not None:
            # persistable marks the tensor itself too: the static
            # Executor writes back mutated persistable captures (BN
            # running stats) after each run, like the reference's
            # persistable-var scope semantics
            tensor.persistable = bool(persistable)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        else:
            self._non_persistable_buffer_names_set.discard(name)
        return tensor

    # ------------------------------------------------------------ lookup
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise ValueError("super().__init__() must be called first")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise ValueError("super().__init__() must be called first")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
            layers[name] = value
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                buffers[name].set_value(value)
        else:
            if params is not None and name in params and value is None:
                params[name] = None
                return
            for d in (params, layers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return (list(super().__dir__()) + list(self._parameters)
                + list(self._sub_layers) + list(self._buffers))

    # --------------------------------------------------------- iteration
    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, l in self.named_children():
            if l is None or id(l) in layers_set:
                continue
            p = prefix + ("." if prefix else "") + name
            yield from l.named_sublayers(prefix=p, include_self=True,
                                         layers_set=layers_set)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        gen = (self.named_sublayers(prefix=prefix, include_self=True)
               if include_sublayers else [(prefix, self)])
        for lp, layer in gen:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield lp + ("." if lp else "") + name, p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        gen = (self.named_sublayers(prefix=prefix, include_self=True)
               if include_sublayers else [(prefix, self)])
        for lp, layer in gen:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield lp + ("." if lp else "") + name, b

    # ------------------------------------------------------------- modes
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = core.convert_dtype(dtype)
            for p in self.parameters():
                if p is not None and p.value.dtype != dtype and \
                        np.issubdtype(np.dtype(p.value.dtype), np.floating):
                    p.value = p.value.astype(dtype)
            for b in self.buffers():
                if b is not None and np.issubdtype(np.dtype(b.value.dtype),
                                                   np.floating):
                    b.value = b.value.astype(dtype)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook):
        h = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[h._id] = hook
        return h

    def register_forward_post_hook(self, hook):
        h = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[h._id] = hook
        return h

    # -------------------------------------------------------------- call
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    # -------------------------------------------------------- state dict
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = collections.OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(
                include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers(
                include_sublayers=include_sublayers):
            # skip non-persistable buffers
            dest[structured_name_prefix + name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                own[k].set_value(v.numpy() if isinstance(v, Tensor) else v)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    def clear_gradients(self):
        for p in self.parameters():
            if p is not None:
                p.clear_grad()

    def full_name(self):
        return self._full_name

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self.named_children():
            rep = repr(l).split("\n")
            rep = [rep[0]] + ["  " + r for r in rep[1:]]
            lines.append(f"  ({name}): " + "\n".join(rep))
        main = self.__class__.__name__ + "(" + extra
        if lines:
            main += "\n" + "\n".join(lines) + "\n"
        return main + ")"
