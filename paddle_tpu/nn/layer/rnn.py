"""Recurrent layers (ref: python/paddle/nn/layer/rnn.py).

TPU-native: sequence iteration is ``lax.scan`` — one compiled loop body, no
per-step kernel launches (contrast ref's cudnn RNN descriptors).  Eager mode
uses the same scan through the dispatch layer so gradients flow on the tape.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from .layers import Layer
from .container import LayerList
from ..initializer import Uniform
from ...ops.dispatch import call
from ...tensor import manipulation as manip
from ...tensor.tensor import Tensor


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ...tensor.creation import full
        B = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape, (list, tuple)) and isinstance(shape[0],
                                                           (list, tuple)):
            return tuple(full([B] + list(s), init_value) for s in shape)
        return full([B] + list(shape), init_value)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def _cell(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        h = call(_cell, inputs, states, self.weight_ih, self.weight_hh,
                 self.bias_ih, self.bias_hh, _name="simple_rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.hidden_size = hidden_size
        self.input_size = input_size

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def _cell(x, hh, cc, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hh @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = f * cc + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        h_new, c_new = call(_cell, inputs, h, c, self.weight_ih,
                            self.weight_hh, self.bias_ih, self.bias_hh,
                            _name="lstm_cell")
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.hidden_size = hidden_size
        self.input_size = input_size

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _cell(x, h, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            return (1 - z) * n + z * h
        h = call(_cell, inputs, states, self.weight_ih, self.weight_hh,
                 self.bias_ih, self.bias_hh, _name="gru_cell")
        return h, h


def _scan_cell(cell, inputs, initial_states, time_major, reverse=False,
               sequence_length=None):
    """Run a cell over time with lax.scan as ONE dispatched primitive.

    ``sequence_length`` follows the reference contract (ref
    fluid/layers/rnn.py::rnn _maybe_copy): STATES freeze once a row's
    valid length is consumed (so final states are the states at
    lengths[b]-1), while outputs stay the raw per-step cell product.
    For a reverse scan the flipped mask means padding steps run first on
    the frozen initial state."""
    params = {k: v for k, v in cell.named_parameters()}
    names = list(params.keys())
    is_lstm = isinstance(cell, LSTMCell)
    masked = sequence_length is not None

    def _run(x, states, lens, *pvals):
        pd = dict(zip(names, pvals))
        wi, wh = pd["weight_ih"], pd["weight_hh"]
        bi, bh = pd["bias_ih"], pd["bias_hh"]
        if not time_major:
            x = jnp.swapaxes(x, 0, 1)  # [T,B,I]
        if reverse:
            x = jnp.flip(x, 0)
        T = x.shape[0]
        if masked:
            mask = (jnp.arange(T)[:, None]
                    < jnp.asarray(lens, jnp.int32)[None, :])  # [T,B]
            if reverse:
                mask = jnp.flip(mask, 0)
        else:
            mask = jnp.ones((T, x.shape[1]), bool)

        def keep(new, old, m):
            return jnp.where(m[:, None], new, old)

        if is_lstm:
            def step(carry, inp):
                xt, m = inp
                h, c = carry
                gates = xt @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i = jax.nn.sigmoid(i); f = jax.nn.sigmoid(f)
                g = jnp.tanh(g); o = jax.nn.sigmoid(o)
                c2 = f * c + i * g
                h2 = o * jnp.tanh(c2)
                return (keep(h2, h, m), keep(c2, c, m)), h2
            carry, ys = jax.lax.scan(step, states, (x, mask))
        elif isinstance(cell, GRUCell):
            def step(h, inp):
                xt, m = inp
                xg = xt @ wi.T + bi
                hg = h @ wh.T + bh
                xr, xz, xn = jnp.split(xg, 3, axis=-1)
                hr, hz, hn = jnp.split(hg, 3, axis=-1)
                r = jax.nn.sigmoid(xr + hr)
                z = jax.nn.sigmoid(xz + hz)
                n = jnp.tanh(xn + r * hn)
                h2 = (1 - z) * n + z * h
                return keep(h2, h, m), h2
            carry, ys = jax.lax.scan(step, states, (x, mask))
        else:
            act = jnp.tanh if cell.activation == "tanh" else jax.nn.relu

            def step(h, inp):
                xt, m = inp
                h2 = act(xt @ wi.T + bi + h @ wh.T + bh)
                return keep(h2, h, m), h2
            carry, ys = jax.lax.scan(step, states, (x, mask))
        if reverse:
            ys = jnp.flip(ys, 0)
        if not time_major:
            ys = jnp.swapaxes(ys, 0, 1)
        return (ys,) + (tuple(carry) if isinstance(carry, tuple) else (carry,))

    pvals = [params[n] for n in names]
    if sequence_length is None:
        batch = inputs.shape[0 if not time_major else 1]
        sequence_length = jnp.full((int(batch),),
                                   inputs.shape[1 if not time_major else 0],
                                   jnp.int32)
    outs = call(_run, inputs, initial_states, sequence_length, *pvals,
                _nondiff=(2,), _name="rnn_scan")
    ys = outs[0]
    final = outs[1:] if len(outs) > 2 else outs[1]
    return ys, final


class RNN(Layer):
    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if initial_states is None:
            batch_idx = 1 if self.time_major else 0
            initial_states = self.cell.get_initial_states(
                inputs, batch_dim_idx=batch_idx)
        return _scan_cell(self.cell, inputs, initial_states, self.time_major,
                          self.is_reverse, sequence_length=sequence_length)


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if initial_states is None:
            batch_idx = 1 if self.time_major else 0
            s_fw = self.cell_fw.get_initial_states(inputs,
                                                   batch_dim_idx=batch_idx)
            s_bw = self.cell_bw.get_initial_states(inputs,
                                                   batch_dim_idx=batch_idx)
        else:
            s_fw, s_bw = initial_states
        y_fw, f_fw = _scan_cell(self.cell_fw, inputs, s_fw, self.time_major,
                                sequence_length=sequence_length)
        y_bw, f_bw = _scan_cell(self.cell_bw, inputs, s_bw, self.time_major,
                                reverse=True,
                                sequence_length=sequence_length)
        out = manip.concat([y_fw, y_bw], axis=-1)
        return out, (f_fw, f_bw)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if bidirect else 1
        self.direction = direction

        def make_cell(isz):
            if mode == "LSTM":
                return LSTMCell(isz, hidden_size, weight_ih_attr,
                                weight_hh_attr, bias_ih_attr, bias_hh_attr)
            if mode == "GRU":
                return GRUCell(isz, hidden_size, weight_ih_attr,
                               weight_hh_attr, bias_ih_attr, bias_hh_attr)
            return SimpleRNNCell(isz, hidden_size, activation, weight_ih_attr,
                                 weight_hh_attr, bias_ih_attr, bias_hh_attr)

        layers = []
        for i in range(num_layers):
            isz = input_size if i == 0 else hidden_size * self.num_directions
            if bidirect:
                layers.append(BiRNN(make_cell(isz), make_cell(isz),
                                    time_major))
            else:
                layers.append(RNN(make_cell(isz),
                                  is_reverse=(direction == "backward"),
                                  time_major=time_major))
        self.layer_list = LayerList(layers)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from .. import functional as F
        out = inputs
        finals = []
        for i, rnn in enumerate(self.layer_list):
            init = None
            if initial_states is not None:
                init = self._layer_state(initial_states, i)
            out, fin = rnn(out, init, sequence_length)
            finals.append(fin)
            if self.dropout > 0 and i < self.num_layers - 1:
                out = F.dropout(out, self.dropout, training=self.training)
        final = self._stack_finals(finals)
        return out, final

    def _layer_state(self, states, i):
        d = self.num_directions
        if self.mode == "LSTM":
            h, c = states
            if d == 1:
                return (h[i * d], c[i * d])
            return ((h[i * d], c[i * d]), (h[i * d + 1], c[i * d + 1]))
        h = states
        if d == 1:
            return h[i * d]
        return (h[i * d], h[i * d + 1])

    def _stack_finals(self, finals):
        d = self.num_directions
        if self.mode == "LSTM":
            hs, cs = [], []
            for fin in finals:
                if d == 2:
                    (h1, c1), (h2, c2) = fin
                    hs += [h1, h2]
                    cs += [c1, c2]
                else:
                    h1, c1 = fin
                    hs.append(h1)
                    cs.append(c1)
            return manip.stack(hs, 0), manip.stack(cs, 0)
        hs = []
        for fin in finals:
            if d == 2:
                f1, f2 = fin
                hs += [f1 if not isinstance(f1, tuple) else f1[0],
                       f2 if not isinstance(f2, tuple) else f2[0]]
            else:
                hs.append(fin if not isinstance(fin, tuple) else fin[0])
        return manip.stack(hs, 0)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__("RNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)
