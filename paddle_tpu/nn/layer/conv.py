"""Conv layers (ref: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import numpy as np

from .layers import Layer
from .. import functional as F
from ..initializer import KaimingUniform, Uniform


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Layer):
    def __init__(self, nd, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transposed=False, output_padding=0):
        super().__init__()
        self._nd = nd
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _tup(kernel_size, nd)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._padding_mode = padding_mode
        self._data_format = data_format
        self._transposed = transposed
        self._output_padding = output_padding
        if transposed:
            wshape = [in_channels, out_channels // groups, *self._kernel_size]
        else:
            wshape = [out_channels, in_channels // groups, *self._kernel_size]
        fan_in = in_channels // groups * int(np.prod(self._kernel_size))
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            shape=wshape, attr=weight_attr,
            default_initializer=KaimingUniform(fan_in=fan_in))
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound))

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={list(self._kernel_size)}, stride={self._stride}")

    def _prepad(self, x):
        """Non-zero padding modes (reflect/replicate/circular) pre-pad the
        input explicitly, then the conv runs unpadded — lax convs only
        zero-pad (ref nn/layer/conv.py applies F.pad the same way)."""
        if self._padding_mode == "zeros":
            return x, self._padding
        pad = self._padding
        if isinstance(pad, int):
            pad = [pad] * self._nd
        pad = [int(p) for p in pad]
        # partial trailing-spatial form, last dim first
        flat = []
        for p in pad[::-1]:
            flat += [p, p]
        x = F.pad(x, flat, mode=self._padding_mode,
                  data_format=self._data_format)
        return x, 0


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        x, padding = self._prepad(x)
        return F.conv1d(x, self.weight, self.bias, self._stride,
                        padding, self._dilation, self._groups,
                        self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        x, padding = self._prepad(x)
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        padding, self._dilation, self._groups,
                        self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        x, padding = self._prepad(x)
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        padding, self._dilation, self._groups,
                        self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)
